"""Dynamic process management tests: connect/accept between two
independently-built jobs, intercomm p2p/collectives/merge, and spawn.

The two-jobs fixture builds two disjoint in-process worlds (separate PML
sets, each with ranks 0..n-1 — exactly the id-collision scenario dpm's
namespace translation exists for) and connects them over a real port.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ompi_tpu.mpi import dpm
from ompi_tpu.mpi.comm import Communicator
from ompi_tpu.mpi.group import Group
from ompi_tpu.mpi.pml import PmlOb1


def _make_world(n: int, name: str) -> list[Communicator]:
    pmls = [PmlOb1(r) for r in range(n)]
    addrs = {r: p.address for r, p in enumerate(pmls)}
    for p in pmls:
        p.set_peers(addrs)
    return [Communicator(Group(range(n)), cid=0, pml=pmls[r],
                         my_world_rank=r, name=name) for r in range(n)]


def _run_two_jobs(na: int, nb: int, job_a, job_b, timeout: float = 30.0):
    """Run job_a(comm) on world A's ranks and job_b(comm) on world B's,
    all on threads; returns (results_a, results_b)."""
    wa, wb = _make_world(na, "A"), _make_world(nb, "B")
    res_a: list = [None] * na
    res_b: list = [None] * nb
    errors: list = []

    def runner(fn, comms, res, rank):
        try:
            res[rank] = fn(comms[rank])
        except BaseException as e:  # noqa: BLE001
            errors.append((fn.__name__, rank, e))

    threads = [threading.Thread(target=runner, args=(job_a, wa, res_a, r),
                                daemon=True) for r in range(na)]
    threads += [threading.Thread(target=runner, args=(job_b, wb, res_b, r),
                                 daemon=True) for r in range(nb)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    alive = [t for t in threads if t.is_alive()]
    try:
        if alive:
            raise TimeoutError(f"{len(alive)} job threads hung "
                               f"(errors: {errors})")
        if errors:
            name, rank, exc = errors[0]
            raise AssertionError(
                f"{name} rank {rank} failed: {exc!r}") from exc
    finally:
        if not alive:
            for c in wa + wb:
                c.pml.close()
    return res_a, res_b


def _with_port(job_a, job_b, na=2, nb=2):
    port = dpm.open_port()
    try:
        return _run_two_jobs(na, nb,
                             lambda c: job_a(c, port),
                             lambda c: job_b(c, port))
    finally:
        dpm.close_port(port)


def test_connect_accept_p2p():
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        assert ic.remote_size == 2
        # my rank r talks to remote rank r
        sreq = ic.isend(np.array([100 + comm.rank]), dest=comm.rank, tag=3)
        got = ic.recv(source=comm.rank, tag=3)
        sreq.wait()
        return int(np.asarray(got)[0])

    def client(comm, port):
        ic = dpm.connect(comm, port)
        assert ic.remote_size == 2
        sreq = ic.isend(np.array([200 + comm.rank]), dest=comm.rank, tag=3)
        got = ic.recv(source=comm.rank, tag=3)
        sreq.wait()
        return int(np.asarray(got)[0])

    res_a, res_b = _with_port(server, client)
    assert res_a == [200, 201]
    assert res_b == [100, 101]


def test_intercomm_bcast_rooted():
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        # server rank 1 is the bcast root toward the client group
        if comm.rank == 1:
            ic.bcast(np.arange(5.0), root="root")
            return None
        from ompi_tpu.mpi.constants import PROC_NULL

        ic.bcast(root=PROC_NULL)  # non-root on the root side
        return None

    def client(comm, port):
        ic = dpm.connect(comm, port)
        out = ic.bcast(root=1)   # receive from remote rank 1
        return np.asarray(out)

    _, res_b = _with_port(server, client)
    for out in res_b:
        np.testing.assert_array_equal(out, np.arange(5.0))


def test_intercomm_merge_allreduce():
    """The merged intracomm must agree on rank order (low group first)
    and run collectives across both original jobs."""
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        m = ic.merge()
        out = m.allreduce(np.array([m.rank], dtype=np.int64))
        return m.rank, int(np.asarray(out)[0])

    def client(comm, port):
        ic = dpm.connect(comm, port)
        m = ic.merge()
        out = m.allreduce(np.array([m.rank], dtype=np.int64))
        return m.rank, int(np.asarray(out)[0])

    res_a, res_b = _with_port(server, client)
    # 4 merged ranks → sum 0+1+2+3 = 6; server (low) ranks 0,1
    assert [r for r, _ in res_a] == [0, 1]
    assert [r for r, _ in res_b] == [2, 3]
    assert all(s == 6 for _, s in res_a + res_b)


def test_intercomm_barrier_and_repeated_connects():
    """Two successive connect/accept pairs between the same jobs must get
    distinct namespaces and cids (regression guard for id collisions)."""
    def server(comm, port):
        ic1 = dpm.accept(comm, port if comm.rank == 0 else None)
        ic1.barrier()
        ic2 = dpm.accept(comm, port if comm.rank == 0 else None)
        ic2.barrier()
        assert ic1.cid != ic2.cid
        got1 = ic1.recv(source=0, tag=9)
        got2 = ic2.recv(source=0, tag=9)
        return int(np.asarray(got1)[0]), int(np.asarray(got2)[0])

    def client(comm, port):
        ic1 = dpm.connect(comm, port)
        ic1.barrier()
        ic2 = dpm.connect(comm, port)
        ic2.barrier()
        if comm.rank == 0:
            ic1.send(np.array([11]), dest=comm.rank, tag=9)
            ic2.send(np.array([22]), dest=comm.rank, tag=9)
        return None

    res_a, _ = _with_port(server, client, na=1, nb=1)
    assert res_a == [(11, 22)]


def test_unknown_port_raises():
    def server(comm):
        from ompi_tpu.mpi.constants import MPIException

        try:
            dpm.accept(comm, "no-such-port:0")
        except MPIException:
            return True
        return False

    res, _ = _run_two_jobs(1, 1, server, lambda c: None)
    assert res == [True]


def test_spawn_parent_child(tmp_path):
    """Full spawn path through the real launcher: parent spawns 2 children,
    exchanges a token over the parent intercomm."""
    child = tmp_path / "child.py"
    child.write_text(
        "import numpy as np\n"
        "import ompi_tpu\n"
        "from ompi_tpu.mpi import dpm\n"
        "comm = ompi_tpu.init()\n"
        "parent = dpm.get_parent(comm)\n"
        "assert parent is not None\n"
        "tok = parent.recv(source=0, tag=7)\n"
        "parent.send(tok * 2, dest=0, tag=8)\n"
        "ompi_tpu.finalize()\n")

    import sys

    world = _make_world(1, "parent")
    try:
        ic = dpm.spawn(world[0], [sys.executable, str(child)], maxprocs=2)
        assert ic.remote_size == 2
        for r in range(2):
            ic.send(np.array([10 + r]), dest=r, tag=7)
        vals = sorted(int(np.asarray(ic.recv(source=r, tag=8))[0])
                      for r in range(2))
        assert vals == [20, 22]
    finally:
        world[0].pml.close()


def test_intercomm_allreduce_swap():
    """≈ coll/inter allreduce: group A's sum lands on B and vice versa."""
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        out = ic.allreduce(np.array([10.0 + comm.rank]))
        return float(np.asarray(out)[0])

    def client(comm, port):
        ic = dpm.connect(comm, port)
        out = ic.allreduce(np.array([1.0 + comm.rank]))
        return float(np.asarray(out)[0])

    res_a, res_b = _with_port(server, client)
    assert res_a == [3.0, 3.0]      # client sum: 1 + 2
    assert res_b == [21.0, 21.0]    # server sum: 10 + 11


def test_intercomm_reduce_rooted():
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        from ompi_tpu.mpi.constants import PROC_NULL

        if comm.rank == 1:
            out = ic.reduce(None, root="root")
            return float(np.asarray(out)[0])
        ic.reduce(None, root=PROC_NULL)
        return None

    def client(comm, port):
        ic = dpm.connect(comm, port)
        # contribute toward remote rank 1
        ic.reduce(np.array([5.0 * (comm.rank + 1)]), root=1)
        return None

    res_a, _ = _with_port(server, client)
    assert res_a[1] == 15.0         # 5 + 10


def test_intercomm_allgather():
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        out = ic.allgather(np.array([100 + comm.rank], dtype=np.int64))
        return np.asarray(out).reshape(-1).tolist()

    def client(comm, port):
        ic = dpm.connect(comm, port)
        out = ic.allgather(np.array([comm.rank], dtype=np.int64))
        return np.asarray(out).reshape(-1).tolist()

    res_a, res_b = _with_port(server, client)
    assert all(r == [0, 1] for r in res_a)        # remote = client data
    assert all(r == [100, 101] for r in res_b)    # remote = server data


def test_intercomm_gather_scatter_rooted():
    def server(comm, port):
        ic = dpm.accept(comm, port if comm.rank == 0 else None)
        from ompi_tpu.mpi.constants import PROC_NULL

        if comm.rank == 0:
            parts = ic.gather(root="root")
            got = [int(np.asarray(p)[0]) for p in parts]
            ic.scatter([np.array([p * 2]) for p in got], root="root")
            return got
        ic.gather(root=PROC_NULL)
        ic.scatter(root=PROC_NULL)
        return None

    def client(comm, port):
        ic = dpm.connect(comm, port)
        ic.gather(np.array([7 + comm.rank]), root=0)
        back = ic.scatter(root=0)
        return int(np.asarray(back)[0])

    res_a, res_b = _with_port(server, client)
    assert res_a[0] == [7, 8]
    assert res_b == [14, 16]
