"""Force the >2-core receiver-pull/poller spin branches and soak them
over REAL processes (VERDICT r4 #4: those branches were tuned blind on a
1-core box).

On one core the spin branches meet their WORST schedule — every spin
iteration steals the quantum the sender process needs — so this is a
liveness stress, not a performance number: the loops must still yield /
back off enough for the frames to arrive, with zero loss or reordering.
The expected multi-core performance is documented in COVERAGE.md (the
branches exist to beat the futex handoff when the sender owns its own
core, the vader fast-box model —
opal/mca/btl/vader/btl_vader_component.c:61-69).

In-process harness ranks ride the proc fast lane (no shm rings), so the
receiver-pull spin only truly engages between processes — hence the
fork rig (same shape as test_native_match.test_shm_two_process_roundtrip).
"""

import multiprocessing as mp

import numpy as np
import pytest

from ompi_tpu.core.config import var_registry
from ompi_tpu.mpi import btl_shm as btl_shm_mod
from ompi_tpu.mpi import pml as pml_mod
from ompi_tpu.mpi.comm import Communicator
from ompi_tpu.mpi.group import Group
from ompi_tpu.mpi.pml import PmlOb1

N_ROUNDS = 40


_REAL_CPU_COUNT = btl_shm_mod.os.cpu_count   # the stdlib function object


def _force_multicore() -> None:
    """Flip both spin-style switches to their >2-core settings.  Called
    in parent AND (via fork inheritance) child before PML construction."""
    pml_mod._SMALL_HOST = False                  # rare-yield pull spin
    btl_shm_mod.os.cpu_count = lambda: 8         # poller spin window
    var_registry.set("btl_shm_spin", 256)


@pytest.fixture
def forced_spin():
    old_spin = var_registry.get("btl_shm_spin")
    old_small = pml_mod._SMALL_HOST
    _force_multicore()
    yield
    # btl_shm_mod.os IS the stdlib os module — restore the saved
    # function object, not a recomputation through the patched one
    btl_shm_mod.os.cpu_count = _REAL_CPU_COUNT
    pml_mod._SMALL_HOST = old_small
    var_registry.set("btl_shm_spin", old_spin)


def test_two_process_soak_under_forced_spin(forced_spin):
    """Mixed eager + rendezvous ping-pong between two real processes with
    the multi-core spin style forced on one core: bounded time, payload
    integrity, and the receiver-pull loop must actually ENGAGE (non-empty
    shm reader list observed during a blocked recv)."""
    sizes = [16, 1 << 12, 1 << 15, 1 << 17]      # eager → rendezvous

    def child(c2p, p2c):
        _force_multicore()                        # fork re-runs nothing;
        # inherited state already forced, but be explicit for clarity
        pml = PmlOb1(1)
        c2p.put(pml.address)
        pml.set_peers(p2c.get())
        comm = Communicator(Group(range(2)), cid=0, pml=pml,
                            my_world_rank=1)
        for i in range(N_ROUNDS):
            n = sizes[i % len(sizes)]
            got = comm.recv(source=0, tag=1)
            assert got.size == n and int(got[0]) == i
            comm.send(np.full(n, i + 1, np.int64), dest=0, tag=2)
        pml.close()

    engaged = {"n": 0}
    orig = PmlOb1._progress_wait

    def spy(self, req):
        shm = self.endpoint.shm_btl
        if shm is not None and shm.reader_list():
            engaged["n"] += 1
        return orig(self, req)

    PmlOb1._progress_wait = spy
    ctx = mp.get_context("fork")
    c2p, p2c = ctx.Queue(), ctx.Queue()
    proc = ctx.Process(target=child, args=(c2p, p2c), daemon=True)
    proc.start()
    pml = PmlOb1(0)
    try:
        peers = {0: pml.address, 1: c2p.get(timeout=30)}
        p2c.put(peers)
        pml.set_peers(peers)
        comm = Communicator(Group(range(2)), cid=0, pml=pml,
                            my_world_rank=0)
        for i in range(N_ROUNDS):
            n = sizes[i % len(sizes)]
            comm.send(np.full(n, i, np.int64), dest=1, tag=1)
            back = comm.recv(source=1, tag=2)
            assert back.size == n and int(back[0]) == i + 1
        proc.join(timeout=30)
        assert proc.exitcode == 0
    finally:
        PmlOb1._progress_wait = orig
        pml.close()
    # the branch under test must have run, not been skipped: once the
    # child's rings exist, blocked recvs enter the pull-spin loop
    assert engaged["n"] > 0, "receiver-pull spin never engaged"
