"""fcoll framework: selectable collective-IO components + job-aware
aggregator selection.

≈ ompi/mca/fcoll — two_phase (static equal file domains,
fcoll_two_phase_file_write_all.c), dynamic (payload-weighted domains),
individual; aggregators one-per-host from the job mapping like OMPIO's
cb_nodes default; the collective_buffering/cb_nodes info hints.
"""

import os

import numpy as np
import pytest

from ompi_tpu.core import config
from ompi_tpu.mpi import io as mio
from ompi_tpu.mpi.constants import MPIException
from ompi_tpu.mpi.datatype import FLOAT
from ompi_tpu.mpi.info import Info
from tests.mpi.harness import run_ranks


@pytest.fixture
def fcoll_var():
    old = config.var_registry.get("io_fcoll")
    yield lambda v: config.var_registry.set("io_fcoll", v)
    config.var_registry.set("io_fcoll", old or "")


def _strided_write(comm, path, fcoll=None, hosts=None, info=None):
    """Each rank writes its column of a (16, size) f32 matrix through a
    strided view; returns the file contents as a matrix."""
    if hosts is not None:
        comm._io_host_override = hosts[comm.rank]
    size = comm.size
    f = mio.File.open(comm, path,
                      mio.MODE_RDWR | mio.MODE_CREATE, info=info)
    ft = FLOAT.vector(16, 1, size)     # one float column of 16 rows
    f.set_view(disp=4 * comm.rank, etype=FLOAT, filetype=ft)
    data = np.full(16, comm.rank, np.float32)
    n = f.write_at_all(0, data)
    assert n == 16
    f.close()
    comm.barrier()
    return np.fromfile(path, np.float32).reshape(16, size)


def _check(mat, size):
    for c in range(size):
        np.testing.assert_array_equal(mat[:, c], np.full(16, c, np.float32))


@pytest.mark.parametrize("comp", ["two_phase", "dynamic", "individual",
                                  "static", "dynamic_gen2"])
def test_forced_components_correct(tmp_path, fcoll_var, comp):
    path = str(tmp_path / f"m_{comp}.bin")
    fcoll_var(comp)

    def body(comm):
        return _strided_write(comm, path)

    run_ranks(4, body)
    _check(np.fromfile(path, np.float32).reshape(16, 4), 4)


def test_unknown_component_raises(tmp_path, fcoll_var):
    fcoll_var("bogus")
    path = str(tmp_path / "x.bin")

    def body(comm):
        with pytest.raises(MPIException, match="bogus"):
            _strided_write(comm, path)
        return None

    run_ranks(2, body)


def test_host_aware_aggregators(tmp_path):
    """Two fake hosts → exactly one aggregator per host (ranks 0 and 2);
    the write must still land correctly through the 2-aggregator plan."""
    path = str(tmp_path / "hosts.bin")
    hosts = ["nodeA", "nodeA", "nodeB", "nodeB"]
    seen = {}

    def body(comm):
        comm._io_host_override = hosts[comm.rank]
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        seen[comm.rank] = f._aggregators()
        f.close()
        return _strided_write(comm, path, hosts=hosts)

    run_ranks(4, body)
    assert seen[0] == [0, 2]            # lowest rank of each host
    assert all(v == [0, 2] for v in seen.values())
    _check(np.fromfile(path, np.float32).reshape(16, 4), 4)


def test_cb_nodes_hint_caps_aggregators(tmp_path):
    path = str(tmp_path / "cap.bin")
    hosts = ["a", "b", "c", "d"]

    def body(comm):
        comm._io_host_override = hosts[comm.rank]
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE,
                          info=Info({"cb_nodes": "2"}))
        aggs = f._aggregators()
        f.close()
        return aggs

    out = run_ranks(4, body)
    assert all(a == [0, 1] for a in out)


def test_collective_buffering_hint_disables(tmp_path):
    """collective_buffering=false must route through individual IO (and
    still produce a correct file)."""
    path = str(tmp_path / "nobuf.bin")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE,
                          info=Info({"collective_buffering": "false"}))
        comp = f._fcoll_component(64, [(0, 4), (8, 4)])
        f.close()
        return comp

    out = run_ranks(2, body)
    assert out == ["individual", "individual"]


def test_auto_decision_skew_picks_dynamic(tmp_path):
    """4x payload skew between ranks → the auto decision goes dynamic."""
    path = str(tmp_path / "skew.bin")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        nbytes = 8192 if comm.rank == 0 else 512
        runs = [(comm.rank * 64, 32), (4096 + comm.rank * 64, 32)]
        comp = f._fcoll_component(nbytes, runs)
        f.close()
        return comp

    out = run_ranks(4, body)
    assert out == ["dynamic"] * 4


def test_dynamic_domain_bounds_balance(tmp_path):
    """dynamic bounds put ~equal payload per aggregator even when the
    file extent is wildly skewed toward one region."""
    path = str(tmp_path / "bal.bin")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        # rank r owns a dense 1KiB run at offset r*1024 plus rank 0 has a
        # huge sparse tail run at 1MiB
        runs = [(comm.rank * 1024, 1024)]
        if comm.rank == 0:
            runs.append((1 << 20, 1024))
        bounds = f._domain_bounds("dynamic", runs, 2)
        f.close()
        return bounds

    out = run_ranks(2, body)
    b = out[0]
    assert b[0] == 0 and b[-1] == (1 << 20) + 1024
    # payload = 3 KiB total → the midpoint boundary must fall inside the
    # dense head region (equal-span bounds would put it at ~512 KiB)
    assert b[1] <= 2048


def test_fs_type_detection(tmp_path):
    """/proc/mounts longest-prefix detection (≈ the statfs-magic checks
    of ompi/mca/fs components)."""
    t = mio._fs_type("/dev/shm") if os.path.isdir("/dev/shm") else None
    if t is not None:
        assert t in ("tmpfs", "ramfs"), t
    # any resolvable path yields a string, never raises
    assert isinstance(mio._fs_type(str(tmp_path)), str)


def test_fs_adaptive_memory_backed_prefers_individual():
    """On tmpfs even a STRIDED pattern (which would normally pick
    two_phase) goes individual: memory-backed writes have no seek cost
    for aggregation to amortize."""
    if not os.path.isdir("/dev/shm"):
        pytest.skip("no /dev/shm")

    import tempfile

    d = tempfile.mkdtemp(dir="/dev/shm")
    path = os.path.join(d, "m.bin")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        assert f.fs_type in ("tmpfs", "ramfs")
        strided = [(comm.rank * 64 + i * 256, 64) for i in range(16)]
        comp = f._fcoll_component(1024, strided)
        # the identical strided pattern on a non-memory fs picks a
        # collective component — the adaptation is doing the deciding
        f.fs_type = "ext4"
        comp_disk = f._fcoll_component(1024, strided)
        f.close()
        return comp, comp_disk

    out = run_ranks(2, body)
    assert all(c == "individual" for c, _ in out)
    assert all(cd == "two_phase" for _, cd in out)
    import shutil

    shutil.rmtree(d, ignore_errors=True)


def test_large_strided_roundtrip_all_components(tmp_path, fcoll_var):
    """Write with one component, read back with another — the file is
    component-independent."""
    path = str(tmp_path / "mix.bin")
    fcoll_var("dynamic_gen2")

    def wr(comm):
        return _strided_write(comm, path)

    run_ranks(4, wr)
    fcoll_var("static")

    def rd(comm):
        size = comm.size
        f = mio.File.open(comm, path, mio.MODE_RDONLY)
        ft = FLOAT.vector(16, 1, size)
        f.set_view(disp=4 * comm.rank, etype=FLOAT, filetype=ft)
        out = f.read_at_all(0, 16)
        f.close()
        np.testing.assert_array_equal(
            out, np.full(16, comm.rank, np.float32))
        return None

    run_ranks(4, rd)


@pytest.mark.parametrize("comp", ["sm", "lockedfile"])
def test_sharedfp_components(tmp_path, comp):
    """Both sharedfp strategies (native shared-memory atomics vs fcntl
    lockedfile) implement the same ordered-reservation contract."""
    from ompi_tpu import _native

    if comp == "sm" and _native.fastdss() is None:
        pytest.skip("native atomics unavailable")
    old = config.var_registry.get("io_sharedfp")
    config.var_registry.set("io_sharedfp", comp)
    path = str(tmp_path / f"sh_{comp}.bin")

    def body(comm):
        from ompi_tpu.mpi.datatype import INT32

        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        assert f._shfp.name == comp
        f.set_view(0, INT32)
        # every rank appends its stamp through the shared pointer; the
        # fetch-add contract → all 4 blocks land disjoint
        data = np.full(8, comm.rank, np.int32)
        f.write_shared(data)
        comm.barrier()
        assert f.get_position_shared() == 32   # 4 ranks x 8 etypes
        f.close()
        return None

    try:
        run_ranks(4, body)
    finally:
        config.var_registry.set("io_sharedfp", old or "")
    blocks = np.fromfile(path, np.int32).reshape(4, 8)
    # each rank's block is uniform, and all ranks appear exactly once
    assert sorted(int(b[0]) for b in blocks) == [0, 1, 2, 3]
    for b in blocks:
        assert (b == b[0]).all()


def test_sharedfp_auto_picks_sm_same_host(tmp_path):
    from ompi_tpu import _native

    if _native.fastdss() is None:
        pytest.skip("native atomics unavailable")
    path = str(tmp_path / "auto.bin")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        name = f._shfp.name
        f.close()
        return name

    assert run_ranks(2, body) == ["sm", "sm"]


def test_sharedfp_auto_lockedfile_cross_host(tmp_path):
    """Ranks on different (fake) hosts cannot share /dev/shm: auto must
    fall back to the lockedfile strategy."""
    path = str(tmp_path / "xhost.bin")
    hosts = ["hostA", "hostB"]

    def body(comm):
        comm._io_host_override = hosts[comm.rank]
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        name = f._shfp.name
        f.close()
        return name

    assert run_ranks(2, body) == ["lockedfile", "lockedfile"]


def test_static_routes_stripes_round_robin(tmp_path):
    """fcoll/static's contract: stripe k goes to aggregator k % naggs
    (cyclic file domains), independent of the bounds partition."""
    path = str(tmp_path / "static.bin")
    old = config.var_registry.get("io_stripe_bytes")
    config.var_registry.set("io_stripe_bytes", 64)
    try:
        def body(comm):
            comm._io_host_override = f"h{comm.rank}"  # every rank an aggregator
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            my_runs = [(comm.rank * 256, 256)]  # 4 stripes each
            aggs = f._aggregators()
            meta, _pay, order = f._route_to_aggregators(
                my_runs, [0, 1024], aggs, None, mode="static")
            f.close()
            # each of my 4 stripes lands on stripe_idx % naggs
            for dest, take in order:
                assert take == 64
            for agg_rank, m in enumerate(meta):
                for off, ln in m:
                    assert (off // 64) % comm.size == agg_rank
            return True

        assert all(run_ranks(4, body))
    finally:
        config.var_registry.set("io_stripe_bytes", old)


def test_dynamic_gen2_bounds_stripe_aligned(tmp_path):
    """dynamic_gen2 = dynamic's payload balance with interior domain
    boundaries snapped to stripe multiples."""
    path = str(tmp_path / "gen2.bin")
    old = config.var_registry.get("io_stripe_bytes")
    config.var_registry.set("io_stripe_bytes", 128)
    try:
        def body(comm):
            comm._io_host_override = f"h{comm.rank}"
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            # skewed payloads: rank r writes (r+1)*100 bytes
            my_runs = [(comm.rank * 1000, (comm.rank + 1) * 100)]
            bounds = f._domain_bounds("dynamic_gen2", my_runs, comm.size)
            f.close()
            for b in bounds[1:-1]:
                assert b % 128 == 0 or b == bounds[0], bounds
            assert bounds == sorted(bounds)
            return True

        assert all(run_ranks(4, body))
    finally:
        config.var_registry.set("io_stripe_bytes", old)
