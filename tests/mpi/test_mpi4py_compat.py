"""mpi4py-compat facade (ompi_tpu.compat.MPI) over the in-process harness.

Each test wraps the harness's native communicators in MPI.Comm and runs
mpi4py-spelled code — the same lines an mpi4py script would contain."""

import numpy as np
import pytest

from ompi_tpu.compat import MPI
from tests.mpi.harness import run_ranks


def wrap(fn):
    return lambda c: fn(MPI.Comm(c))


def test_send_recv_buffer_spec_and_status():
    def fn(comm):
        rank = comm.Get_rank()
        if rank == 0:
            buf = np.arange(8, dtype=np.float64)
            comm.Send([buf, MPI.DOUBLE], dest=1, tag=7)
            return None
        out = np.zeros(8, dtype=np.float64)
        st = MPI.Status()
        comm.Recv(out, source=MPI.ANY_SOURCE, tag=MPI.ANY_TAG, status=st)
        assert st.Get_source() == 0
        assert st.Get_tag() == 7
        assert st.Get_count(MPI.DOUBLE) == 8
        assert st.Get_count(MPI.BYTE) == 64      # unit conversion
        assert st.Get_count(MPI.INT32_T) == 16
        return out

    res = run_ranks(2, wrap(fn))
    np.testing.assert_array_equal(res[1], np.arange(8, dtype=np.float64))


def test_lowercase_objects_roundtrip():
    def fn(comm):
        if comm.rank == 0:
            comm.send({"k": [1, 2, 3], "s": "hello"}, dest=1, tag=3)
            req = comm.irecv(source=1, tag=4)
            return req.wait()
        obj = comm.recv(source=0, tag=3)
        comm.isend(("reply", obj["k"]), dest=0, tag=4).Wait()
        return obj

    res = run_ranks(2, wrap(fn))
    assert res[0] == ("reply", [1, 2, 3])
    assert res[1] == {"k": [1, 2, 3], "s": "hello"}


def test_lowercase_collectives():
    def fn(comm):
        rank, size = comm.rank, comm.size
        got = comm.bcast({"root": "payload"} if rank == 0 else None, root=0)
        assert got == {"root": "payload"}
        gathered = comm.gather(f"r{rank}", root=0)
        if rank == 0:
            assert gathered == [f"r{r}" for r in range(size)]
        else:
            assert gathered is None
        all_objs = comm.allgather(rank * 10)
        assert all_objs == [r * 10 for r in range(size)]
        mine = comm.scatter([f"part{r}" for r in range(size)]
                            if rank == 0 else None, root=0)
        assert mine == f"part{rank}"
        swapped = comm.alltoall([(rank, r) for r in range(size)])
        assert swapped == [(r, rank) for r in range(size)]
        total = comm.allreduce(rank + 1)
        assert total == sum(r + 1 for r in range(size))
        rtot = comm.reduce(rank + 1, op=MPI.SUM, root=0)
        assert (rtot == total) if rank == 0 else (rtot is None)
        pre = comm.scan(rank + 1)
        assert pre == sum(r + 1 for r in range(rank + 1))
        epre = comm.exscan(rank + 1)
        if rank == 0:
            assert epre is None
        else:
            assert epre == sum(r + 1 for r in range(rank))
        return True

    assert all(run_ranks(4, wrap(fn)))


def test_uppercase_collectives():
    def fn(comm):
        rank, size = comm.rank, comm.size
        buf = np.full(4, rank, np.float64) if rank == 0 else np.zeros(
            4, np.float64)
        comm.Bcast(buf, root=0)
        np.testing.assert_array_equal(buf, np.zeros(4))

        send = np.full(3, rank + 1.0)
        recv = np.zeros(3)
        comm.Allreduce(send, recv, op=MPI.SUM)
        np.testing.assert_array_equal(
            recv, np.full(3, sum(r + 1.0 for r in range(size))))

        # IN_PLACE
        acc = np.full(3, rank + 1.0)
        comm.Allreduce(MPI.IN_PLACE, acc, op=MPI.MAX)
        np.testing.assert_array_equal(acc, np.full(3, float(size)))

        out = np.zeros(size, np.int64)
        comm.Allgather(np.array([rank], np.int64), out)
        np.testing.assert_array_equal(out, np.arange(size))

        gat = np.zeros(size, np.int64) if rank == 0 else None
        comm.Gather(np.array([rank], np.int64), gat, root=0)
        if rank == 0:
            np.testing.assert_array_equal(gat, np.arange(size))

        part = np.zeros(2, np.int64)
        comm.Scatter(np.arange(2 * size, dtype=np.int64)
                     if rank == 0 else None, part, root=0)
        np.testing.assert_array_equal(part, [2 * rank, 2 * rank + 1])

        a2a = np.zeros(size, np.int64)
        comm.Alltoall(np.full(size, rank, np.int64), a2a)
        np.testing.assert_array_equal(a2a, np.arange(size))

        red = np.zeros(2) if rank == 0 else None
        comm.Reduce(np.array([rank + 1.0, 1.0]), red, op=MPI.PROD, root=0)
        if rank == 0:
            want = np.prod([r + 1.0 for r in range(size)])
            np.testing.assert_allclose(red, [want, 1.0])

        sc = np.zeros(1)
        comm.Scan(np.array([float(rank + 1)]), sc, op=MPI.SUM)
        assert sc[0] == sum(r + 1 for r in range(rank + 1))
        return True

    assert all(run_ranks(4, wrap(fn)))


def test_scatterv_gatherv_counts_displs():
    def fn(comm):
        rank, size = comm.rank, comm.size
        counts = [r + 1 for r in range(size)]
        displs = list(np.concatenate([[0], np.cumsum(counts)[:-1]]))
        total = sum(counts)
        recv = np.zeros(counts[rank])
        comm.Scatterv([np.arange(total, dtype=np.float64)
                       if rank == 0 else np.zeros(0),
                       counts, displs, MPI.DOUBLE], recv, root=0)
        np.testing.assert_array_equal(
            recv, np.arange(displs[rank], displs[rank] + counts[rank]))

        out = np.zeros(total) if rank == 0 else None
        comm.Gatherv(recv, out, root=0)
        if rank == 0:
            np.testing.assert_array_equal(out, np.arange(total))
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_uppercase_wait_lands_nonblocking_collectives():
    """req.Wait() (capital — the mpi4py buffer API) must run the landing
    copy into the receive buffer, exactly like lowercase .wait()."""
    def fn(comm):
        rank = comm.rank
        buf = np.full(4, float(rank), np.float64)
        req = comm.Ibcast(buf, root=0)
        req.Wait()
        np.testing.assert_array_equal(buf, np.zeros(4))

        send = np.full(2, float(rank + 1), np.float64)
        recv = np.zeros(2)
        comm.Iallreduce(send, recv, op=MPI.SUM).Wait()
        total = sum(r + 1 for r in range(comm.size))
        np.testing.assert_array_equal(recv, np.full(2, float(total)))

        # Waitall must land every transform too
        recv2 = np.zeros(2)
        buf2 = np.full(4, float(rank), np.float64)
        MPI.Request.Waitall([comm.Iallreduce(send, recv2, op=MPI.SUM),
                             comm.Ibcast(buf2, root=0)])
        np.testing.assert_array_equal(recv2, np.full(2, float(total)))
        np.testing.assert_array_equal(buf2, np.zeros(4))
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_gatherv_respects_displs():
    """The recv spec's counts/displs place each rank's piece — including
    gaps (poison must survive in the unwritten bytes)."""
    def fn(comm):
        rank, size = comm.rank, comm.size
        send = np.full(2, float(rank), np.float64)
        counts = [2] * size
        displs = [4 * r + 1 for r in range(size)]  # stride 4: gaps of 2
        out = np.full(4 * size, -1.0) if rank == 0 else None
        spec = [out, counts, displs, MPI.DOUBLE] if rank == 0 else None
        comm.Gatherv(send, spec, root=0)
        if rank == 0:
            want = np.full(4 * size, -1.0)
            for r in range(size):
                want[displs[r]:displs[r] + 2] = float(r)
            np.testing.assert_array_equal(out, want)

        # Allgatherv with the same layout on every rank
        all_out = np.full(4 * size, -1.0)
        comm.Allgatherv(send, [all_out, counts, displs, MPI.DOUBLE])
        want = np.full(4 * size, -1.0)
        for r in range(size):
            want[displs[r]:displs[r] + 2] = float(r)
        np.testing.assert_array_equal(all_out, want)
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_status_is_cancelled():
    def fn(comm):
        if comm.rank == 0:
            out = np.zeros(4)
            req = comm.Irecv(out, source=1, tag=99)
            req.Cancel()
            st = MPI.Status()
            req.Wait(st)
            assert st.Is_cancelled()
        comm.Barrier()
        return True

    assert all(run_ranks(2, wrap(fn)))


def test_reduce_scatter_with_counts():
    def fn(comm):
        rank, size = comm.rank, comm.size
        counts = [2] * size
        send = np.arange(2 * size, dtype=np.float64)
        recv = np.zeros(2)
        comm.Reduce_scatter(send, recv, recvcounts=counts, op=MPI.SUM)
        np.testing.assert_array_equal(
            recv, size * np.arange(2 * rank, 2 * rank + 2, dtype=np.float64))
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_sendrecv_and_replace():
    def fn(comm):
        rank, size = comm.rank, comm.size
        right, left = (rank + 1) % size, (rank - 1) % size
        got = comm.sendrecv(f"from{rank}", dest=right, sendtag=1,
                            source=left, recvtag=1)
        assert got == f"from{left}"
        buf = np.full(2, rank, np.int64)
        comm.Sendrecv_replace(buf, dest=right, sendtag=2, source=left,
                              recvtag=2)
        np.testing.assert_array_equal(buf, [left, left])
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_probe_and_matched_probe():
    def fn(comm):
        if comm.rank == 0:
            comm.send([1, 2], dest=1, tag=11)
            comm.Send(np.arange(3, dtype=np.int32), dest=1, tag=12)
            return True
        st = MPI.Status()
        assert comm.Probe(source=0, tag=11, status=st)
        assert st.Get_tag() == 11
        assert comm.recv(source=0, tag=11) == [1, 2]
        msg = comm.Mprobe(source=0, tag=12, status=st)
        assert st.Get_tag() == 12
        buf = np.zeros(3, np.int32)
        msg.Recv(buf)
        np.testing.assert_array_equal(buf, np.arange(3))
        return True

    assert all(run_ranks(2, wrap(fn)))


def test_persistent_requests():
    def fn(comm):
        rank = comm.rank
        if rank == 0:
            buf = np.zeros(4, np.float64)
            req = comm.Send_init(buf, dest=1, tag=5)
            for i in range(3):
                buf[:] = i
                req.Start()
                req.Wait()
            return True
        buf = np.zeros(4, np.float64)
        req = comm.Recv_init(buf, source=0, tag=5)
        seen = []
        for _ in range(3):
            req.Start()
            req.Wait()
            seen.append(buf.copy())
        return seen

    res = run_ranks(2, wrap(fn))
    for i, arr in enumerate(res[1]):
        np.testing.assert_array_equal(arr, np.full(4, float(i)))


def test_comm_management_and_groups():
    def fn(comm):
        rank, size = comm.rank, comm.size
        dup = comm.Dup()
        assert dup.Get_size() == size
        dup.Free()

        evens = comm.Split(color=rank % 2, key=rank)
        assert evens.Get_size() == len(range(rank % 2, size, 2))
        assert evens.Get_rank() == rank // 2
        evens.Free()

        g = comm.Get_group()
        assert g.Get_size() == size
        assert g.Get_rank() == rank
        sub_g = g.Incl([0, 1])
        sub = comm.Create_group(sub_g) if rank in (0, 1) else None
        if rank in (0, 1):
            assert sub is not None
            assert sub.Get_size() == 2
            total = sub.allreduce(1)
            assert total == 2
            sub.Free()
        return True

    assert all(run_ranks(4, wrap(fn)))


def test_user_op_and_waitall():
    def fn(comm):
        rank, size = comm.rank, comm.size
        op = MPI.Op.Create(lambda a, b: a + b, commute=True)
        assert comm.allreduce([rank], op=op) == list(range(size))

        if rank == 0:
            reqs = [comm.isend(i * 100, dest=1, tag=20 + i)
                    for i in range(3)]
            MPI.Request.Waitall(reqs)
            return True
        reqs = [comm.irecv(source=0, tag=20 + i) for i in range(3)]
        vals = MPI.Request.waitall(reqs)
        assert vals == [0, 100, 200]
        return True

    assert all(run_ranks(2, wrap(fn)))


def test_nonblocking_collectives():
    def fn(comm):
        rank, size = comm.rank, comm.size
        req = comm.Ibarrier()
        req.Wait()

        buf = (np.arange(4, dtype=np.float64) if rank == 0
               else np.zeros(4))
        comm.Ibcast(buf, root=0).wait()
        np.testing.assert_array_equal(buf, np.arange(4))

        send = np.full(2, rank + 1.0)
        recv = np.zeros(2)
        comm.Iallreduce(send, recv, op=MPI.SUM).wait()
        np.testing.assert_array_equal(
            recv, np.full(2, sum(r + 1.0 for r in range(size))))
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_datatype_and_constants_surface():
    assert MPI.DOUBLE.Get_size() == 8
    assert MPI.INT32_T.np_dtype == np.int32
    assert MPI.ANY_SOURCE < 0 and MPI.ANY_TAG < 0
    assert MPI.SUM(2, 3) == 5
    assert MPI.MAX(2, 3) == 3
    assert MPI.LXOR(True, False) is True
    assert MPI.Op.Create(lambda a, b: a * b)(3, 4) == 12
    assert MPI.THREAD_MULTIPLE == 3


def test_iprobe_negative():
    def fn(comm):
        if comm.rank == 1:
            assert comm.Iprobe(source=0, tag=99) is False
        comm.barrier()
        return True

    assert all(run_ranks(2, wrap(fn)))


def test_win_put_get_accumulate_fence():
    def fn(comm):
        rank, size = comm.rank, comm.size
        mem = np.zeros(8, np.float64)
        win = MPI.Win.Create(mem, disp_unit=mem.itemsize, comm=comm)
        win.Fence()
        # everyone puts its rank into slot `rank` of the right neighbor
        right = (rank + 1) % size
        win.Put(np.full(1, float(rank)), right, target=rank)
        win.Fence()
        left = (rank - 1) % size
        assert mem[left] == float(left), mem
        # accumulate into rank 0's slot 7
        win.Accumulate(np.ones(1), 0, target=7, op=MPI.SUM)
        win.Fence()
        if rank == 0:
            assert mem[7] == float(size), mem
        got = np.zeros(1)
        win.Lock(0, MPI.LOCK_SHARED)
        win.Get(got, 0, target=7)
        win.Unlock(0)
        assert got[0] == float(size)
        # fetch-and-op round
        old = np.zeros(1)
        win.Lock(0)
        win.Fetch_and_op(np.ones(1), old, 0, target_disp=6, op=MPI.SUM)
        win.Unlock(0)
        win.Fence()
        win.Free()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_file_collective_and_shared(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("compatio") / "f.bin")

    def fn(comm):
        rank, size = comm.rank, comm.size
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR | MPI.MODE_CREATE)
        data = np.full(4, float(rank), np.float64)
        f.Write_at_all(rank * 4 * 8, data)
        f.Close()
        comm.Barrier()
        f = MPI.File.Open(comm, path, MPI.MODE_RDONLY)
        back = np.zeros(4, np.float64)
        f.Read_at((((rank + 1) % size) * 4) * 8, back)
        np.testing.assert_array_equal(
            back, np.full(4, float((rank + 1) % size)))
        assert f.Get_size() == size * 4 * 8
        f.Close()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_file_views_seek_shared_ordered(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("compatio2") / "v.bin")

    def fn(comm):
        rank, size = comm.rank, comm.size
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR | MPI.MODE_CREATE)
        # mpi4py idiom: scalar etype + scalar filetype view.  The disp
        # stride must keep the 2-double windows DISJOINT across ranks:
        # concurrent overlapping access without atomic mode is undefined
        # per MPI-IO, and the coll/shm barrier releases ranks close
        # enough together to surface the race an 8*rank stride had.
        f.Set_view(disp=16 * rank, etype=MPI.DOUBLE, filetype=MPI.DOUBLE)
        f.Write_at(0, np.full(2, float(rank)))   # offsets in DOUBLEs
        f.Seek(0)
        assert f.Get_position() == 0
        back = np.zeros(2)
        f.Read(back)
        np.testing.assert_array_equal(back, np.full(2, float(rank)))
        assert f.Get_position() == 2
        f.Close()
        comm.Barrier()

        # ordered writes: rank order through the shared pointer
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR)
        f.Write_ordered(np.full(2, 100.0 + rank))
        f.Close()
        comm.Barrier()
        if rank == 0:
            got = np.fromfile(path, np.float64)[:2 * size]
            want = np.repeat(100.0 + np.arange(size), 2)
            np.testing.assert_array_equal(got, want)
        comm.Barrier()

        # shared-pointer writes land without overlap
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR)
        f.Write_shared(np.full(1, float(10 + rank)))
        f.Sync()
        f.Close()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_win_count_validation():
    def fn(comm):
        mem = np.zeros(4, np.float64)
        win = MPI.Win.Create(mem, disp_unit=8, comm=comm)
        win.Fence()
        try:
            import pytest

            with pytest.raises(MPI.Exception, match="count"):
                win.Put(np.ones(2), 0, target=[0, 4])
        finally:
            win.Fence()
            win.Free()
        return True

    assert all(run_ranks(2, wrap(fn)))


def test_uniform_collectives_skip_list_roundtrip():
    """Uppercase Allgather/Gather/Alltoall take the stacked-ndarray fast
    path: the native result lands in the recv buffer without a per-rank
    python list + concatenate round-trip (mpi4py users' expectation that
    uppercase = zero-copy)."""
    # structural guarantee: an ndarray passes through _stacked by identity
    arr = np.arange(12.0).reshape(3, 4)
    assert MPI.Comm._stacked(arr) is arr
    # list fallback still concatenates (intercomm/object paths)
    out = MPI.Comm._stacked([np.ones(2), np.zeros(2)])
    np.testing.assert_array_equal(out, [1, 1, 0, 0])

    # and the native collectives really do hand the facade an ndarray
    def fn(comm):
        got = np.zeros(comm.size * 4, np.float64)
        comm.Allgather(np.full(4, float(comm.rank)), got)
        want = np.repeat(np.arange(comm.size, dtype=np.float64), 4)
        np.testing.assert_array_equal(got, want)
        a2a = np.zeros(comm.size * 2, np.float64)
        comm.Alltoall(np.repeat(np.arange(comm.size, dtype=np.float64), 2),
                      a2a)
        np.testing.assert_array_equal(a2a, np.full(comm.size * 2,
                                                   float(comm.rank)))
        return True

    assert all(run_ranks(4, wrap(fn)))


def test_win_allocate_typed_roundtrip():
    """The standard mpi4py idiom: Win.Allocate(nbytes) + Put/Get of TYPED
    buffers must be a bitwise copy, not a value-cast into 0..255."""
    def fn(comm):
        rank = comm.rank
        win = MPI.Win.Allocate(8 * 8, disp_unit=8, comm=comm)
        win.Fence()
        vals = np.array([3.25e9, -1.5, 0.125], np.float64)
        if rank == 0:
            win.Put(vals, 1, target=2)       # disp 2 doubles into rank 1
        win.Fence()
        if rank == 1:
            mem = np.asarray(win.memory).view(np.float64)
            np.testing.assert_array_equal(mem[2:5], vals)
        # typed Get reads the bytes back as float64
        got = np.zeros(3, np.float64)
        win.Lock(1, MPI.LOCK_SHARED)
        win.Get(got, 1, target=2)
        win.Unlock(1)
        np.testing.assert_array_equal(got, vals)
        # REPLACE accumulate is a bitwise put; arithmetic ops must refuse
        win.Fence()
        if rank == 0:
            win.Accumulate(vals * 2, 1, target=2, op=MPI.REPLACE)
            import pytest

            with pytest.raises(MPI.Exception, match="uint8 origin"):
                win.Accumulate(vals, 1, target=2, op=MPI.SUM)
        win.Fence()
        if rank == 1:
            mem = np.asarray(win.memory).view(np.float64)
            np.testing.assert_array_equal(mem[2:5], vals * 2)
        # Get_accumulate with REPLACE: old typed value comes back
        old = np.zeros(3, np.float64)
        if rank == 0:
            win.Lock(1)
            win.Get_accumulate(vals, old, 1, target=2, op=MPI.REPLACE)
            win.Unlock(1)
            np.testing.assert_array_equal(old, vals * 2)
        # single-element atomics can't reinterpret a typed operand into
        # one byte — they refuse instead of value-casting
        if rank == 0:
            import pytest

            res = np.zeros(1)
            with pytest.raises(MPI.Exception, match="uint8 origin"):
                win.Fetch_and_op(np.array([3.25e9]), res, 1, 0, op=MPI.SUM)
            with pytest.raises(MPI.Exception, match="uint8 origin"):
                win.Compare_and_swap(np.array([1.5]), np.zeros(1), res, 1)
            # uint8 operands still work
            win.Lock(1)
            win.Fetch_and_op(np.array([2], np.uint8),
                             np.zeros(1, np.uint8), 1, 0, op=MPI.SUM)
            win.Unlock(1)
        win.Fence()
        win.Free()
        return True

    assert all(run_ranks(2, wrap(fn)))


def test_reduce_local_and_pickle_hook():
    """Op.Reduce_local (local fold, no communication) and the MPI.pickle
    serializer hook the lowercase API routes through."""
    b = np.array([10.0, 20.0])
    MPI.SUM.Reduce_local(np.array([1.0, 2.0]), b)
    np.testing.assert_array_equal(b, [11.0, 22.0])
    MPI.MAX.Reduce_local(np.array([100.0, 1.0]), b)
    np.testing.assert_array_equal(b, [100.0, 22.0])

    # equal-counts contract enforced (no silent broadcast/truncate);
    # native-layer errors surface as the native MPIException (a
    # RuntimeError, like MPI.Exception)
    import pytest
    with pytest.raises(RuntimeError, match="shape"):
        MPI.SUM.Reduce_local(np.ones(1), b)

    assert isinstance(MPI.pickle, MPI.Pickle)
    calls = []

    def my_dumps(obj, protocol):
        calls.append(1)
        import pickle as std

        return std.dumps(obj, protocol)

    orig = MPI.pickle
    # the PUBLIC swap idiom: replace the whole serializer instance
    MPI.pickle = MPI.Pickle(dumps=my_dumps)
    try:
        def fn(comm):
            return comm.bcast({"v": 7} if comm.rank == 0 else None,
                              root=0)

        out = run_ranks(2, wrap(fn))
        assert out[1]["v"] == 7 and calls
    finally:
        MPI.pickle = orig


def test_win_allocate_shared_and_dynamic():
    """Win.Allocate_shared (osc/sm: one segment, zero-copy Shared_query
    views) and Win.Create_dynamic + Attach/Detach."""
    def fn(comm):
        rank, size = comm.rank, comm.size
        node = comm.Split_type(MPI.COMM_TYPE_SHARED)
        win = MPI.Win.Allocate_shared(8, disp_unit=1, comm=node)
        nbytes, du, mine = win.Shared_query(node.Get_rank())
        assert nbytes == 8 and du == 1
        mine[:] = node.Get_rank() + 1
        win.Fence()                     # sync: stores visible to peers
        for r in range(node.Get_size()):
            _n, _d, view = win.Shared_query(r)
            assert view[0] == r + 1, (r, view[:2])
        assert win.Get_attr(MPI.WIN_SIZE) == 8
        # the RMA verbs work as memcpy on the mapping (osc/sm): put a
        # byte into my RIGHT neighbor's slice, fence, check mine
        nrank, nsize = node.Get_rank(), node.Get_size()
        win.Lock((nrank + 1) % nsize)   # coherence-only, must not raise
        win.Put(np.full(1, 200, np.uint8), (nrank + 1) % nsize,
                target=4)
        win.Unlock((nrank + 1) % nsize)
        win.Fence()
        assert mine[4] == 200
        got = np.zeros(1, np.uint8)
        win.Get(got, (nrank + 1) % nsize, target=0)
        assert got[0] == (nrank + 1) % nsize + 1
        import pytest
        with pytest.raises(MPI.Exception, match="PSCW"):
            win.Start(node.Get_group())
        win.Fence()
        win.Free()                      # unlinks the /dev/shm segment
        # dynamic window: expose a region, peers Put at its base offset
        dyn = MPI.Win.Create_dynamic(comm=comm)
        region = np.zeros(4, np.uint8)
        base = dyn.Attach(region)
        dyn.Fence()
        peer = (rank + 1) % size
        bases = comm.allgather(base)
        dyn.Put(np.full(2, 7, np.uint8), peer, target=bases[peer])
        dyn.Fence()
        assert region[0] == 7 and region[1] == 7, region
        dyn.Detach(base)
        dyn.Free()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_win_request_rma_and_file_management():
    """Request-based RMA (Rput/Rget land on Wait) + Group/Win/File
    management accessors."""
    def fn(comm):
        rank, size = comm.rank, comm.size
        mem = np.zeros(4, np.float64)
        win = MPI.Win.Create(mem, disp_unit=8, comm=comm)
        win.Lock((rank + 1) % size)
        r = win.Rput(np.full(1, float(rank + 1)), (rank + 1) % size,
                     target=0)
        r.Wait()
        win.Flush((rank + 1) % size)
        win.Unlock((rank + 1) % size)
        comm.Barrier()
        assert mem[0] == float((rank - 1) % size + 1)
        got = np.zeros(1)
        win.Lock((rank + 1) % size, MPI.LOCK_SHARED)
        win.Rget(got, (rank + 1) % size, target=0).Wait()
        win.Unlock((rank + 1) % size)
        assert got[0] == float(rank + 1)
        g = win.Get_group()
        assert g.Get_size() == size and g.Get_rank() == rank
        assert g.Compare(comm.Get_group()) == MPI.IDENT
        win.Free()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_file_management_accessors(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("iomgmt")
    path = str(tmp / "m.bin")

    def fn(comm):
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR | MPI.MODE_CREATE)
        assert f.Get_amode() & MPI.MODE_RDWR
        ft = MPI.DOUBLE.Create_vector(4, 1, comm.size).Commit()
        f.Set_view(disp=8 * comm.rank, etype=MPI.DOUBLE, filetype=ft)
        disp, et, ftype = f.Get_view()
        assert disp == 8 * comm.rank
        assert et == MPI.DOUBLE                 # facade round-trip
        assert ftype.Get_size() == ft.Get_size()
        assert f.Get_byte_offset(2) >= 16       # 2 etypes into the view
        f.Set_size(0)                            # collective truncate
        f.Write_at_all(0, np.arange(4, dtype=np.float64) + comm.rank)
        assert f.Get_size() > 0
        f.Close()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_nonblocking_collective_family_lands_in_buffers():
    """Igather/Iscatter/Iallgather/Ialltoall/Iscan/Iexscan land their
    results into the caller's buffer on Wait (transform path)."""
    def fn(comm):
        rank, size = comm.rank, comm.size
        g = np.zeros(size * 2, np.float64)
        r = comm.Igather(np.full(2, float(rank)), g, root=0)
        r.Wait()
        if rank == 0:
            np.testing.assert_array_equal(
                g, np.repeat(np.arange(size, dtype=np.float64), 2))
        sc = np.zeros(2, np.float64)
        r = comm.Iscatter(
            np.repeat(np.arange(size, dtype=np.float64), 2)
            if rank == 0 else None, sc, root=0)
        r.Wait()
        np.testing.assert_array_equal(sc, [float(rank)] * 2)
        ag = np.zeros(size, np.float64)
        comm.Iallgather(np.full(1, float(rank)), ag).Wait()
        np.testing.assert_array_equal(ag, np.arange(size))
        a2a = np.zeros(size, np.float64)
        comm.Ialltoall(np.full(size, float(rank)), a2a).Wait()
        np.testing.assert_array_equal(a2a, np.arange(size))
        sn = np.zeros(1, np.float64)
        comm.Iscan(np.ones(1), sn).Wait()
        assert sn[0] == rank + 1
        ex = np.zeros(1, np.float64)
        comm.Iexscan(np.ones(1), ex).Wait()
        if rank:
            assert ex[0] == rank
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_alltoallv_attrs_info_errhandler_compare():
    def fn(comm):
        rank, size = comm.rank, comm.size
        # Alltoallv: rank r sends k+1 items to rank k
        counts = [k + 1 for k in range(size)]
        displs = np.concatenate([[0], np.cumsum(counts)[:-1]]).tolist()
        send = np.concatenate(
            [np.full(k + 1, float(rank * 10 + k)) for k in range(size)])
        rcounts = [rank + 1] * size
        rdispls = (np.arange(size) * (rank + 1)).tolist()
        recv = np.zeros(size * (rank + 1), np.float64)
        comm.Alltoallv([send, counts, displs, MPI.DOUBLE],
                       [recv, rcounts, rdispls, MPI.DOUBLE])
        for src in range(size):
            np.testing.assert_array_equal(
                recv[src * (rank + 1):(src + 1) * (rank + 1)],
                np.full(rank + 1, float(src * 10 + rank)))
        # Alltoallw, mpi4py message format: [buf, counts, displs, dts]
        # — every peer exchanges 2 doubles here
        wsend = np.concatenate(
            [np.full(2, float(rank * 10 + k)) for k in range(size)])
        wrecv = np.zeros(size * 2, np.float64)
        bytes_displs = (np.arange(size) * 16).tolist()
        comm.Alltoallw(
            [wsend, [2] * size, bytes_displs, [MPI.DOUBLE] * size],
            [wrecv, [2] * size, bytes_displs, [MPI.DOUBLE] * size])
        for src in range(size):
            np.testing.assert_array_equal(
                wrecv[src * 2:(src + 1) * 2],
                np.full(2, float(src * 10 + rank)))
        # attributes + TAG_UB + keyvals
        assert comm.Get_attr(MPI.TAG_UB) > 1 << 20
        kv = MPI.Comm.Create_keyval()
        comm.Set_attr(kv, {"x": rank})
        assert comm.Get_attr(kv)["x"] == rank
        comm.Delete_attr(kv)
        assert comm.Get_attr(kv) is None
        # info
        info = MPI.Info.Create({"k": "v"})
        assert info.Get("k") == "v" and info.Get_nkeys() == 1
        comm.Set_info(info)
        assert comm.Get_info().Get("k") == "v"
        # errhandler round-trip
        old = comm.Get_errhandler()
        comm.Set_errhandler(MPI.ERRORS_RETURN)
        assert comm.Get_errhandler() is not None
        comm.Set_errhandler(old)
        # compare
        assert comm.Compare(comm) == MPI.IDENT
        dup = comm.Dup()
        assert comm.Compare(dup) == MPI.CONGRUENT
        dup.Free()
        assert comm.Get_topology() == MPI.UNDEFINED
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_dist_graph_and_idup():
    def fn(comm):
        rank, size = comm.rank, comm.size
        # ring dist graph: I receive from left, send to right
        dg = comm.Create_dist_graph_adjacent(
            [(rank - 1) % size], [(rank + 1) % size])
        assert dg is not None
        ns, nd = dg.Get_dist_neighbors()
        assert list(ns) == [(rank - 1) % size]
        assert list(nd) == [(rank + 1) % size]
        assert dg.Get_topology() == MPI.DIST_GRAPH
        dup, req = comm.Idup()        # mpi4py order: (newcomm, request)
        req.Wait()
        assert dup.Get_size() == size
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_file_nonblocking_and_split_collectives(tmp_path_factory):
    """mpi4py File nonblocking (Iwrite_at/Iread_at land on Wait) and the
    split collective Begin/End pairs."""
    tmp = tmp_path_factory.mktemp("ionb")
    path = str(tmp / "nb.bin")

    def fn(comm):
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR | MPI.MODE_CREATE)
        data = np.arange(8, dtype=np.float64) + 10 * comm.rank
        req = f.Iwrite_at(8 * comm.rank * 8, data)
        assert req.Wait()
        comm.Barrier()
        back = np.zeros(8, np.float64)
        r2 = f.Iread_at(8 * comm.rank * 8, back)
        r2.Wait()
        np.testing.assert_array_equal(back, data)
        # split collective write + read (MPI requires the SAME buffer at
        # begin and end)
        data2 = data * 2
        f.Write_at_all_begin(8 * comm.rank * 8, data2)
        f.Write_at_all_end(data2)
        comm.Barrier()
        out = np.zeros(8, np.float64)
        f.Read_at_all_begin(8 * comm.rank * 8, out)
        f.Read_at_all_end(out)
        np.testing.assert_array_equal(out, data * 2)
        f.Close()
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_datatype_create_family_file_views(tmp_path_factory):
    """The mpi4py derived-type idiom drives file views end to end:
    Create_vector(...).Commit() as a filetype interleaves the ranks;
    Create_indexed_block picks scattered blocks; extent/size surface."""
    tmp = tmp_path_factory.mktemp("dtcompat")
    path = str(tmp / "v.bin")

    vec = MPI.DOUBLE.Create_vector(8, 1, 3).Commit()
    assert vec.Get_size() == 8 * 8          # payload bytes per tile
    assert vec.Get_extent()[1] == 8 * ((8 - 1) * 3 + 1)
    idx = MPI.INT32_T.Create_indexed_block(2, [0, 6])
    assert idx.Get_size() == 2 * 2 * 4
    sub = MPI.DOUBLE.Create_subarray([4, 4], [2, 2], [1, 1])
    assert sub.Get_size() == 4 * 8
    stc = MPI.Datatype.Create_struct([1, 1], [0, 8],
                                     [MPI.DOUBLE, MPI.INT32_T])
    assert stc.Get_size() == 12
    hib = MPI.DOUBLE.Create_hindexed_block(1, [0, 24])
    assert hib.Get_size() == 16
    # darray: rank 0's block of an 8x8 block-distributed grid on 2x2
    da = MPI.DOUBLE.Create_darray(
        4, 0, [8, 8], [MPI.DISTRIBUTE_BLOCK, MPI.DISTRIBUTE_BLOCK],
        [MPI.DISTRIBUTE_DFLT_DARG, MPI.DISTRIBUTE_DFLT_DARG], [2, 2])
    assert da.Get_size() == 4 * 4 * 8        # a 4x4 block of doubles
    vec.Free()                               # no-ops, mpi4py parity

    def fn(comm):
        f = MPI.File.Open(comm, path, MPI.MODE_RDWR | MPI.MODE_CREATE)
        ft = MPI.DOUBLE.Create_vector(8, 1, comm.size).Commit()
        f.Set_view(disp=8 * comm.rank, etype=MPI.DOUBLE, filetype=ft)
        data = np.arange(8, dtype=np.float64) + 10 * comm.rank
        f.Write_at_all(0, data)
        back = np.zeros(8, np.float64)
        f.Read_at_all(0, back)
        f.Close()
        np.testing.assert_array_equal(back, data)
        return True

    assert all(run_ranks(3, wrap(fn)))
    disk = np.fromfile(path, np.float64)
    # interleave: position 3*i + r holds rank r's i-th value
    for r in range(3):
        np.testing.assert_array_equal(
            disk[r::3][:8], np.arange(8, dtype=np.float64) + 10 * r)


def test_cartcomm_create_shift_sub():
    """mpi4py Cartesian topology surface: Create_cart, Get_topo,
    Get_coords/Get_cart_rank inverses, Shift with PROC_NULL at edges,
    Sub, Compute_dims."""
    assert MPI.Compute_dims(6, 2) == [3, 2]

    def fn(comm):
        rank = comm.rank
        cart = comm.Create_cart([2, 2], periods=[True, False])
        assert cart is not None
        dims, periods, coords = cart.Get_topo()
        assert dims == [2, 2] and periods == [True, False]
        assert cart.Get_dim() == 2
        assert cart.Get_cart_rank(coords) == cart.Get_rank()
        assert cart.Get_coords(cart.Get_rank()) == coords

        # dim 0 periodic: both directions defined
        src, dst = cart.Shift(0, 1)
        assert src != MPI.PROC_NULL and dst != MPI.PROC_NULL
        # dim 1 non-periodic: the edge sees PROC_NULL
        src1, dst1 = cart.Shift(1, 1)
        if coords[1] == 1:
            assert dst1 == MPI.PROC_NULL
        if coords[1] == 0:
            assert src1 == MPI.PROC_NULL

        # ring exchange along the periodic dim through the topology
        s, d = cart.Shift(0, 1)
        got = np.zeros(1, np.int64)
        cart.Sendrecv(np.array([rank], np.int64), d, 0, got, s, 0)
        row = cart.Sub([True, False])
        assert row.Get_size() == 2
        return True

    assert all(run_ranks(4, wrap(fn)))


def test_cart_default_periods_is_nonperiodic():
    """mpi4py's Create_cart defaults periods to all-False (the native
    layer's torus default must not leak through)."""
    def fn(comm):
        cart = comm.Create_cart([comm.size])
        assert cart.periods == [False]
        src, dst = cart.Shift(0, 1)
        if cart.coords[0] == 0:
            assert src == MPI.PROC_NULL
        if cart.coords[0] == comm.size - 1:
            assert dst == MPI.PROC_NULL
        assert cart.dim == 1 and cart.dims == [comm.size]
        return True

    assert all(run_ranks(3, wrap(fn)))


def test_spawn_get_parent_merge(tmp_path_factory):
    """mpi4py DPM surface end to end through the real launcher: Spawn,
    child-side Comm.Get_parent, pickled + buffer p2p over the
    intercomm, Disconnect."""
    import subprocess
    import sys as _sys

    tmp = tmp_path_factory.mktemp("compatspawn")
    child = tmp / "child.py"
    child.write_text(
        "import numpy as np\n"
        "from ompi_tpu.compat import MPI\n"
        "parent = MPI.Comm.Get_parent()\n"
        "assert parent is not None\n"
        "obj = parent.recv(source=0, tag=7)\n"
        "parent.send({'double': obj * 2}, dest=0, tag=8)\n"
        "buf = np.zeros(2)\n"
        "parent.Recv(buf, source=0, tag=9)\n"
        "parent.Send(buf + 1.0, dest=0, tag=10)\n"
        "import os\n"
        "if int(os.environ['OMPI_TPU_RANK']) == 1:\n"
        "    parent.send('from-one', dest=0, tag=11)\n"
        "parent.Disconnect()\n"
        "MPI.Finalize()\n")

    def fn(comm):
        ic = comm.Spawn(_sys.executable, args=[str(child)], maxprocs=2)
        assert ic.Get_remote_size() == 2
        for r in range(2):
            ic.send(10 + r, dest=r, tag=7)
        got = sorted(ic.recv(source=r, tag=8)["double"] for r in range(2))
        assert got == [20, 22]
        for r in range(2):
            ic.Send(np.array([1.5, 2.5]), r, tag=9)
        back = np.zeros(2)
        ic.Recv(back, source=0, tag=10)
        np.testing.assert_array_equal(back, [2.5, 3.5])
        ic.Recv(back, source=1, tag=10)
        # mpi4py default source is ANY_SOURCE: a message from a NONZERO
        # remote rank must match a default-args recv
        st = MPI.Status()
        msg = ic.recv(tag=11, status=st)
        assert msg == "from-one" and st.Get_source() == 1
        ic.Disconnect()
        return True

    assert all(run_ranks(1, wrap(fn)))


def test_graphcomm_create_neighbors():
    """mpi4py graph topology: ring graph, neighbors per rank."""
    def fn(comm):
        size = comm.size
        # ring: each node connects to (r-1, r+1)
        index, edges = [], []
        for r in range(size):
            edges += [(r - 1) % size, (r + 1) % size]
            index.append(len(edges))
        g = comm.Create_graph(index, edges)
        assert g is not None
        assert g.Get_dims() == (size, 2 * size)
        me = g.Get_rank()
        assert sorted(g.Get_neighbors(me)) == sorted(
            [(me - 1) % size, (me + 1) % size])
        assert g.Get_neighbors_count(me) == 2
        gi, ge = g.Get_topo()
        assert gi == index and ge == edges
        return True

    assert all(run_ranks(3, wrap(fn)))
