"""Matched probe: MPI_Mprobe/Improbe/Mrecv/Imrecv.

≈ the reference's ompi/mpi/c/mprobe.c, improbe.c, mrecv.c, imrecv.c —
the MPI-3 thread-safe probe-then-receive: the probe atomically detaches
the matched message from the unexpected queue, so no other thread's recv
or probe can steal it between the probe and the receive.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ompi_tpu.mpi.constants import ANY_SOURCE, ANY_TAG, PROC_NULL, MPIException
from tests.mpi.harness import run_ranks


def test_mprobe_mrecv_eager():
    def body(comm):
        if comm.rank == 0:
            comm.send(np.arange(8, dtype=np.int32), dest=1, tag=7)
            return None
        msg, st = comm.mprobe(source=0, tag=7, timeout=30)
        assert st.source == 0
        assert st.tag == 7
        assert st.count == 8
        out = comm.mrecv(message=msg)
        np.testing.assert_array_equal(out, np.arange(8, dtype=np.int32))
        return out

    run_ranks(2, body)


def test_improbe_none_then_match():
    def body(comm):
        if comm.rank == 0:
            comm.barrier()          # rank 1 improbes before anything sent
            comm.send(np.float64(3.25), dest=1, tag=1)
            return None
        assert comm.improbe(source=0, tag=1) is None
        comm.barrier()
        # poll with a deadline until the frame lands (delivery is async)
        import time

        out = None
        deadline = time.monotonic() + 30
        while out is None and time.monotonic() < deadline:
            out = comm.improbe(source=0, tag=1)
            if out is None:
                time.sleep(0.001)
        assert out is not None
        msg, st = out
        assert st.source == 0
        val = comm.mrecv(message=msg)
        assert float(val) == 3.25
        return None

    run_ranks(2, body)


def test_detached_message_invisible_to_recv_and_probe():
    """Once detached, the message must not match any other recv/probe."""

    def body(comm):
        if comm.rank == 0:
            comm.send(np.int32(111), dest=1, tag=5)
            comm.barrier()
            comm.send(np.int32(222), dest=1, tag=5)
            return None
        msg, _st = comm.mprobe(source=0, tag=5, timeout=30)
        # same-signature probe/recv must NOT see the detached message
        assert comm.iprobe(source=0, tag=5) is None
        rreq = comm.irecv(source=ANY_SOURCE, tag=ANY_TAG)
        assert not rreq.done()
        comm.barrier()
        second = rreq.wait()        # matches the SECOND send only
        assert int(second) == 222
        first = comm.mrecv(message=msg)
        assert int(first) == 111
        return None

    run_ranks(2, body)


def test_mprobe_rendezvous():
    """A detached rendezvous message pulls its data at mrecv time."""

    def body(comm):
        n = 1 << 18                 # 1 MiB of float32 — well past eager
        if comm.rank == 0:
            comm.send(np.arange(n, dtype=np.float32), dest=1, tag=9)
            return None
        msg, st = comm.mprobe(source=ANY_SOURCE, tag=9, timeout=30)
        assert st.count == n
        out = comm.mrecv(message=msg)
        np.testing.assert_array_equal(out, np.arange(n, dtype=np.float32))
        return None

    run_ranks(2, body)


def test_ssend_completes_at_mprobe():
    """A sync-mode send is 'matched' when mprobe detaches it — the sender
    must complete even if mrecv is delayed."""

    def body(comm):
        if comm.rank == 0:
            req = comm.issend(np.int32(5), dest=1, tag=3)
            req.wait(timeout=30)    # must complete on the mprobe alone
            comm.barrier()
            return None
        msg, _ = comm.mprobe(source=0, tag=3, timeout=30)
        comm.barrier()              # sender already completed by now
        assert int(comm.mrecv(message=msg)) == 5
        return None

    run_ranks(2, body)


def test_two_thread_mprobe_race():
    """Two receiver threads mprobe(ANY_SOURCE) concurrently: each message
    is delivered to exactly one thread, none duplicated, none lost —
    the guarantee plain probe cannot give."""

    def body(comm):
        if comm.rank in (0, 1):
            payload = np.full(4, 100 + comm.rank, dtype=np.int64)
            comm.send(payload, dest=2, tag=77)
            return None
        got = []
        lock = threading.Lock()

        def receiver():
            msg, _st = comm.pml.mprobe(ANY_SOURCE, 77, comm.cid,
                                       timeout=30)
            out = comm.pml.mrecv(None, msg)
            with lock:
                got.append(int(out[0]))

        ts = [threading.Thread(target=receiver) for _ in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert sorted(got) == [100, 101]
        return None

    run_ranks(3, body)


def test_imrecv_status_group_rank():
    """On a communicator whose group order differs from world order,
    imrecv must report the GROUP rank in status.source (same as mrecv) —
    and the value must be translated before the waiter can observe it."""
    from ompi_tpu.mpi.comm import Communicator
    from ompi_tpu.mpi.group import Group

    def body(world):
        # reversed group: world rank 0 ↔ group rank 1 and vice versa
        rev = Communicator(Group([1, 0]), cid=77, pml=world.pml,
                           my_world_rank=world.pml.rank,
                           name="reversed")
        me = rev.rank                      # group rank
        if me == 1:                        # world rank 0
            rev.send(np.arange(4, dtype=np.int32), dest=0, tag=6)
            return None
        msg, st = rev.mprobe(source=1, tag=6, timeout=30)
        assert st.source == 1              # group rank of the sender
        req = rev.imrecv(np.zeros(4, np.int32), message=msg)
        req.wait(timeout=30)
        assert req.status.source == 1      # translated, not world rank 0
        return None

    run_ranks(2, body)


def test_mprobe_proc_null():
    def body(comm):
        msg, st = comm.mprobe(source=PROC_NULL)
        assert msg.no_proc
        assert st.source == PROC_NULL
        assert st.count == 0
        out = comm.mrecv(message=msg)
        assert out.size == 0
        return None

    run_ranks(1, body)


def test_message_double_consume_raises():
    def body(comm):
        if comm.rank == 0:
            comm.send(np.int32(1), dest=1, tag=2)
            return None
        msg, _ = comm.mprobe(source=0, tag=2, timeout=30)
        comm.mrecv(message=msg)
        with pytest.raises(MPIException):
            comm.mrecv(message=msg)
        return None

    run_ranks(2, body)


def test_imrecv_into_posted_buffer():
    def body(comm):
        if comm.rank == 0:
            comm.send(np.arange(16, dtype=np.float32), dest=1, tag=4)
            return None
        msg, _ = comm.mprobe(source=0, tag=4, timeout=30)
        buf = np.zeros(16, dtype=np.float32)
        req = comm.imrecv(buf, message=msg)
        req.wait(timeout=30)
        np.testing.assert_array_equal(buf, np.arange(16, dtype=np.float32))
        assert req.status.source == 0
        assert req.status.tag == 4
        assert req.status.count == 16
        return None

    run_ranks(2, body)
