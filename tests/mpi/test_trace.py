"""Flight-recorder tests: ring-buffer semantics, end-to-end category
coverage across the transport stack, Chrome-trace export + merge,
metrics snapshot, crash dump, and the always-on fast-path counters."""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from ompi_tpu.mpi import datatype as dt
from ompi_tpu.mpi import trace
from tests.mpi.harness import run_ranks

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[2] / "tools"))

import trace_export  # noqa: E402


@pytest.fixture(autouse=True)
def _trace_off_after():
    """Every test leaves the global recorder disarmed."""
    yield
    trace.disable()


# ---------------------------------------------------------------------------
# ring buffer
# ---------------------------------------------------------------------------

def test_ring_buffer_wraps_oldest_first():
    rec = trace.FlightRecorder(capacity=32, rank=0)
    for i in range(100):
        rec.add(i, None, "pml", f"e{i}", 0, None)
    assert rec.events_total == 100
    assert rec.dropped == 68
    snap = rec.snapshot()
    assert len(snap) == 32
    # oldest surviving event is #68, newest is #99, in order
    assert snap[0][3] == "e68" and snap[-1][3] == "e99"
    assert [e[0] for e in snap] == list(range(68, 100))


def test_disabled_emit_is_noop():
    assert trace.recorder is None and not trace.active
    trace.instant("pml", "nope")            # no recorder: nothing happens
    trace.complete("pml", "nope", trace.begin())
    with trace.span("pml", "nope"):
        pass


def test_enable_disable_cycle():
    rec = trace.enable(capacity=64, rank=3, jobid=9)
    assert trace.active and trace.enabled()
    trace.instant("runtime", "hello", rank=3)
    got = trace.disable()
    assert got is rec and not trace.active
    assert got.snapshot()[-1][3] == "hello"


def test_reenable_adopts_later_identity():
    """enable() before the rank is known, then again with rank/jobid
    (what runtime.init does): the recorder must adopt the identity so
    ranks don't all flush to the shared rank--1 path."""
    rec = trace.enable(capacity=64)
    assert rec.rank == -1 and rec.jobid == 0
    assert trace.enable(rank=3, jobid=7) is rec
    assert rec.rank == 3 and rec.jobid == 7
    assert trace.default_path().endswith("ompi_tpu_trace_7_rank3.json")


def test_disable_detaches_pml_listener():
    """disable() must remove the attach_pml listener — a leftover one
    keeps the PML's eager fast lane (gated on no-listeners) bypassed
    after tracing stops."""
    trace.enable(capacity=64)

    def body(comm):
        trace.attach_pml(comm.pml)
        assert comm.pml._listeners
        trace.disable()
        return len(comm.pml._listeners)

    assert run_ranks(2, body) == [0, 0]


def test_detach_pml_scoped_to_one_pml():
    """finalize()'s per-epoch detach removes only that PML's bridge
    (other PMLs — in-process harness ranks — keep theirs)."""
    trace.enable(capacity=64)

    def body(comm):
        trace.attach_pml(comm.pml)
        comm.barrier()
        if comm.rank == 0:
            trace.detach_pml(comm.pml)
        comm.barrier()
        return len(comm.pml._listeners)

    assert sorted(run_ranks(2, body)) == [0, 1]


# ---------------------------------------------------------------------------
# end-to-end: the whole stack feeds the timeline
# ---------------------------------------------------------------------------

def test_stack_categories_end_to_end(tmp_path):
    trace.enable(capacity=16384)

    def body(comm):
        from ompi_tpu.mpi import io as mpiio

        trace.attach_pml(comm.pml)
        peer = (comm.rank + 1) % comm.size
        # eager p2p
        r = comm.irecv(source=(comm.rank - 1) % comm.size, tag=1)
        comm.send(np.arange(32, dtype=np.float64), dest=peer, tag=1)
        r.wait()
        # rendezvous p2p (past the 64 KiB eager limit)
        big = np.ones(128 * 1024, dtype=np.float32)
        r = comm.irecv(np.empty_like(big),
                       source=(comm.rank - 1) % comm.size, tag=2)
        comm.send(big, dest=peer, tag=2)
        r.wait()
        # coll
        comm.allreduce(np.ones(4))
        comm.barrier()
        # datatype: derived commit + pack through the wire
        vec = dt.INT32.vector(count=8, blocklength=2, stride=4).commit()
        r = comm.irecv(np.empty(16, np.int32),
                       source=(comm.rank - 1) % comm.size, tag=3,
                       datatype=dt.INT32, count=16)
        comm.send(np.arange(32, dtype=np.int32), dest=peer, tag=3,
                  datatype=vec, count=1)
        r.wait()
        # io
        fh = mpiio.File(comm, str(tmp_path / "trace_io.bin"),
                        mpiio.MODE_RDWR | mpiio.MODE_CREATE)
        fh.set_view(etype=dt.FLOAT64)
        fh.write_at(comm.rank * 8, np.full(8, 1.0 + comm.rank))
        out = fh.read_at(comm.rank * 8, 8)
        fh.close()
        return float(out[0])

    vals = run_ranks(2, body)
    assert vals == [1.0, 2.0]
    events = trace.recorder.snapshot()
    span_cats = {e[2] for e in events if e[1] is not None}
    # the acceptance bar: spans from ≥ 5 categories
    assert {"pml", "coll", "io", "datatype"} <= span_cats
    inst_cats = {e[2] for e in events if e[1] is None}
    assert "btl" in inst_cats        # endpoint routing instants
    assert len(span_cats | inst_cats) >= 5
    # the PERUSE bridge put the request lifecycle on the timeline
    names = {e[3] for e in events}
    assert {"send_post", "recv_post", "match", "deliver"} <= names
    # rendezvous got begin/end spans on both sides
    assert "rndv_send" in names and "rndv_recv" in names


def test_flow_ids_pair_send_and_recv_spans():
    """Cross-rank trace correlation: eager and rndv frames carry a flow
    id (args.fl) recorded on BOTH the send-side span and the matching
    recv-side span — the raw material for the exporter's Perfetto flow
    arrows."""
    trace.enable(capacity=65536)

    def body(comm):
        trace.attach_pml(comm.pml)   # listeners off the eager fast lane
        peer = (comm.rank + 1) % comm.size
        r = comm.irecv(source=(comm.rank - 1) % comm.size, tag=1)
        comm.send(np.arange(32, dtype=np.float64), dest=peer, tag=1)
        r.wait()
        big = np.ones(128 * 1024, dtype=np.float32)
        r = comm.irecv(np.empty_like(big),
                       source=(comm.rank - 1) % comm.size, tag=2)
        comm.send(big, dest=peer, tag=2)
        r.wait()
        return 0

    assert run_ranks(2, body) == [0, 0]
    events = trace.recorder.snapshot()
    by_name: dict[str, set] = {}
    for _ts, dur, _cat, name, _rank, args in events:
        if dur is not None and name in ("eager_send", "eager_recv",
                                        "rndv_send", "rndv_recv"):
            fl = (args or {}).get("fl")
            if fl:
                by_name.setdefault(name, set()).add(fl)
    # each send span's fl shows up on a recv span (per protocol class)
    assert by_name.get("eager_send") and \
        by_name["eager_send"] & by_name.get("eager_recv", set())
    assert by_name.get("rndv_send") and \
        by_name["rndv_send"] & by_name.get("rndv_recv", set())
    # flow ids are globally unique: rank-strided namespaces don't collide
    all_fl = [f for s in by_name.values() for f in s]
    assert any(f >= 1 << 40 for f in all_fl), \
        "rank 1's flow ids should ride the stride namespace"


def test_flow_ids_cost_nothing_when_tracing_off():
    """With the recorder disarmed, frames carry no fl key at all."""
    assert not trace.active

    def body(comm):
        peer = (comm.rank + 1) % comm.size
        seen = []
        orig = comm.pml._enqueue_frame

        def spy(p, hdr, payload, req):
            seen.append(dict(hdr))
            return orig(p, hdr, payload, req)

        comm.pml._enqueue_frame = spy
        try:
            r = comm.irecv(source=(comm.rank - 1) % comm.size, tag=1)
            comm.send(np.ones(4096, dtype=np.float64), dest=peer, tag=1)
            r.wait()
        finally:
            comm.pml._enqueue_frame = orig
        return sum(1 for h in seen if "fl" in h)

    assert run_ranks(2, body) == [0, 0]


def test_export_flow_events_synthesized():
    """The exporter turns matching send/recv fl spans into a Perfetto
    flow pair (ph s → ph f, bind-to-enclosing) anchored inside the
    spans, and skips unpaired or same-rank flows."""
    evs = [
        {"ph": "X", "name": "eager_send", "cat": "pml", "ts": 100.0,
         "dur": 5.0, "pid": 0, "tid": 0, "args": {"fl": 42}},
        {"ph": "X", "name": "eager_recv", "cat": "pml", "ts": 110.0,
         "dur": 3.0, "pid": 1, "tid": 0, "args": {"fl": 42}},
        # unpaired send: no arrow
        {"ph": "X", "name": "rndv_send", "cat": "pml", "ts": 200.0,
         "dur": 5.0, "pid": 0, "tid": 0, "args": {"fl": 7}},
        # self-send (same pid both halves): no arrow
        {"ph": "X", "name": "eager_send", "cat": "pml", "ts": 300.0,
         "dur": 1.0, "pid": 0, "tid": 0, "args": {"fl": 8}},
        {"ph": "X", "name": "eager_recv", "cat": "pml", "ts": 302.0,
         "dur": 1.0, "pid": 0, "tid": 0, "args": {"fl": 8}},
        # cross-host clock skew: recv span ends BEFORE the send span's
        # end — no binding placement exists, pair skipped
        {"ph": "X", "name": "eager_send", "cat": "pml", "ts": 400.0,
         "dur": 10.0, "pid": 0, "tid": 0, "args": {"fl": 9}},
        {"ph": "X", "name": "eager_recv", "cat": "pml", "ts": 395.0,
         "dur": 2.0, "pid": 1, "tid": 0, "args": {"fl": 9}},
    ]
    flows = trace_export.flow_events(evs)
    assert len(flows) == 2
    s, f = flows
    assert s["ph"] == "s" and f["ph"] == "f" and f["bp"] == "e"
    assert s["id"] == f["id"] == 42
    assert s["pid"] == 0 and f["pid"] == 1
    # endpoints land inside their spans
    assert 100.0 <= s["ts"] <= 105.0
    assert 110.0 <= f["ts"] <= 113.0
    # flow events pass the exporter's own validation
    doc = {"displayTimeUnit": "ns",
           "traceEvents": sorted(evs + flows, key=lambda e: e["ts"])}
    assert trace_export.validate(doc) == []


def test_export_merge_emits_flow_arrows(tmp_path):
    """End-to-end: two per-rank dumps with matching fl spans merge into
    a trace containing s/f flow events."""
    def dump(rank, name, ts, fl):
        doc = {"displayTimeUnit": "ns",
               "otherData": {"rank": rank, "jobid": 5,
                             "clock_offset_ns": 0},
               "traceEvents": [
                   {"ph": "X", "name": name, "cat": "pml", "ts": ts,
                    "dur": 4.0, "pid": rank, "tid": 0,
                    "args": {"fl": fl}}]}
        p = tmp_path / f"ompi_tpu_trace_5_rank{rank}.json"
        p.write_text(json.dumps(doc))
        return str(p)

    paths = [dump(0, "eager_send", 10.0, 99),
             dump(1, "eager_recv", 20.0, 99)]
    doc = trace_export.merge(paths)
    phases = [e["ph"] for e in doc["traceEvents"]]
    assert "s" in phases and "f" in phases
    assert trace_export.validate(doc) == []


def test_coll_span_records_rules_decision(tmp_path):
    from ompi_tpu.core.config import var_registry

    rules = tmp_path / "rules.conf"
    rules.write_text("allreduce 0 0 ring\n")
    old = var_registry.get("coll_host_dynamic_rules")
    trace.enable(capacity=4096)
    try:
        var_registry.set("coll_host_dynamic_rules", str(rules))

        def body(comm):
            comm.allreduce(np.ones(8, dtype=np.float64))
            return True

        assert all(run_ranks(2, body))
    finally:
        var_registry.set("coll_host_dynamic_rules", old)
    events = trace.recorder.snapshot()
    decisions = [e for e in events if e[3] == "decision:allreduce"]
    assert decisions, "rules decision never hit the timeline"
    assert decisions[-1][5]["algorithm"] == "ring"
    assert "rules.conf" in decisions[-1][5]["source"]
    assert any(e[3] == "allreduce" and e[1] is not None for e in events)


# ---------------------------------------------------------------------------
# export: per-rank dumps → merged Chrome trace
# ---------------------------------------------------------------------------

def _fake_rank_dump(tmp_path, rank: int) -> str:
    rec = trace.FlightRecorder(capacity=128, rank=rank, jobid=7)
    t0 = 1_000_000 + rank          # deterministic, distinct timestamps
    rec.add(t0, 500, "pml", "send_post", rank, {"peer": 1 - rank})
    rec.add(t0 + 1000, None, "btl", "send", rank, None)
    rec.add(t0 + 2000, 300, "coll", "allreduce", rank, None)
    path = str(tmp_path / f"ompi_tpu_trace_7_rank{rank}.json")
    trace.flush(path=path, rec=rec)
    return path


def test_export_merges_ranks_into_chrome_trace(tmp_path):
    paths = [_fake_rank_dump(tmp_path, r) for r in (0, 1)]
    doc = trace_export.merge(paths)
    assert doc["displayTimeUnit"] == "ns"
    assert trace_export.validate(doc) == []
    evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    assert {e["pid"] for e in evs} == {0, 1}          # one pid per rank
    # one tid per category, named by metadata events
    meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    thread_names = {(m["pid"], m["args"]["name"]) for m in meta
                    if m["name"] == "thread_name"}
    assert (0, "pml") in thread_names and (1, "coll") in thread_names
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    # spans kept their duration (ns → µs)
    spans = [e for e in evs if e["ph"] == "X"]
    assert spans and all("dur" in e for e in spans)


def test_export_cli_writes_and_validates(tmp_path, capsys):
    for r in (0, 1):
        _fake_rank_dump(tmp_path, r)
    out = str(tmp_path / "merged.json")
    rc = trace_export.main(["--dir", str(tmp_path), "--jobid", "7",
                            "-o", out])
    assert rc == 0
    doc = json.load(open(out))
    assert trace_export.validate(doc) == []
    assert trace_export.main([out, "--validate"]) == 0
    assert trace_export.main(["--dir", str(tmp_path / "empty")]) == 2


def test_export_warns_on_mixed_job_dumps(tmp_path, capsys):
    """Same rank from two different jobids: the merge must warn — their
    monotonic clocks share no base."""
    paths = []
    for jobid in (1, 2):
        rec = trace.FlightRecorder(capacity=8, rank=0, jobid=jobid)
        rec.add(1000, None, "pml", "x", 0, None)
        p = str(tmp_path / f"ompi_tpu_trace_{jobid}_rank0.json")
        trace.flush(path=p, rec=rec)
        paths.append(p)
    trace_export.merge(paths)
    err = capsys.readouterr().err
    assert "WARNING" in err and "--jobid" in err


def test_sigterm_flush_handler_installs_once():
    """enable→disable→enable must not chain the flush handler onto
    itself (a self-referential chain recurses inside the handler)."""
    import signal

    old = signal.getsignal(signal.SIGTERM)
    saved_flag, saved_old = trace._sigterm_installed, trace._old_sigterm
    try:
        trace._sigterm_installed = False
        trace._install_sigterm_flush()
        h1 = signal.getsignal(signal.SIGTERM)
        assert h1 is not old
        trace._install_sigterm_flush()      # second arm: no re-chain
        assert signal.getsignal(signal.SIGTERM) is h1
        assert trace._old_sigterm is not h1  # never chained onto itself
    finally:
        signal.signal(signal.SIGTERM, old)
        trace._sigterm_installed = saved_flag
        trace._old_sigterm = saved_old


def test_shm_publish_counter_counts_only_successful_publishes():
    """A FrameTooBig publish must not bump btl_shm_publish_total."""
    from ompi_tpu.mpi.btl_shm import FrameTooBig, ShmBTL

    got = []
    a = ShmBTL(0, lambda p, h, b: got.append(b))
    b = ShmBTL(1, lambda p, h, b: got.append(b))
    try:
        assert a.connect(1, b.address)
        before = trace.counters["btl_shm_publish_total"]
        a.send(1, {"t": "eager", "tag": 1, "cid": 0, "seq": 0,
                   "dt": "<u1", "elems": 4, "shp": [4]}, b"\x01" * 4)
        assert trace.counters["btl_shm_publish_total"] == before + 1
        with pytest.raises(FrameTooBig):
            a.send(1, {"t": "eager"}, b"\x00" * (8 << 20))  # > ring/2
        assert trace.counters["btl_shm_publish_total"] == before + 1
    finally:
        a.close()
        b.close()


def test_flush_coerces_non_json_args(tmp_path):
    """Apps pass numpy scalars into traced calls (e.g. np.int32 ranks to
    Window.post); flush must coerce, not raise mid-finalize."""
    rec = trace.FlightRecorder(capacity=16, rank=0, jobid=0)
    rec.add(10, None, "osc", "post", 0,
            {"origins": [np.int32(1)], "odd": object()})
    path = str(tmp_path / "coerce.json")
    assert trace.flush(path=path, rec=rec) == path
    doc = json.load(open(path))
    args = doc["traceEvents"][-1]["args"]
    assert args["origins"] == [1]
    assert isinstance(args["odd"], str)


def test_sigterm_chain_preserves_sig_ign():
    """A process that was IGNORING SIGTERM must keep ignoring it after
    the flush runs — the chain must not reset to SIG_DFL and re-kill."""
    import signal

    old = signal.getsignal(signal.SIGTERM)
    saved_flag, saved_old = trace._sigterm_installed, trace._old_sigterm
    try:
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        trace._sigterm_installed = False
        trace._install_sigterm_flush()
        handler = signal.getsignal(signal.SIGTERM)
        handler(signal.SIGTERM, None)   # must return, not kill us
    finally:
        signal.signal(signal.SIGTERM, old)
        trace._sigterm_installed = saved_flag
        trace._old_sigterm = saved_old


def test_validator_rejects_broken_traces():
    bad = {"displayTimeUnit": "parsec", "traceEvents": [
        {"ph": "X", "ts": -5, "pid": 0, "tid": 0, "name": "x"},
        {"ph": "X", "ts": 1.0, "pid": 0, "tid": 0, "name": "y"},  # no dur
    ]}
    problems = trace_export.validate(bad)
    assert any("displayTimeUnit" in p for p in problems)
    assert any("bad ts" in p for p in problems)
    assert any("without dur" in p for p in problems)


# ---------------------------------------------------------------------------
# crash dump + metrics
# ---------------------------------------------------------------------------

def test_crash_dump_writes_default_path(tmp_path, monkeypatch):
    monkeypatch.setenv("TMPDIR", str(tmp_path))
    trace.enable(capacity=256, rank=4, jobid=12)
    trace.instant("runtime", "before_the_end", rank=4)
    path = trace.crash_dump(reason="test")
    assert path == str(tmp_path / "ompi_tpu_trace_12_rank4.json")
    doc = json.load(open(path))
    assert doc["displayTimeUnit"] == "ns"
    names = {e["name"] for e in doc["traceEvents"]}
    assert "before_the_end" in names
    assert "crash_dump:test" in names       # the reason is on the timeline
    assert doc["otherData"]["rank"] == 4
    assert "counters" in doc["otherData"]


def test_metrics_snapshot_prometheus_shape():
    text = trace.metrics_snapshot()
    lines = text.strip().splitlines()
    assert any(ln.startswith("# TYPE ompi_tpu_") for ln in lines)
    assert any(ln.startswith("# HELP ompi_tpu_") for ln in lines)
    # every registered always-on counter is scrapable
    for name, _u, _d in trace._COUNTER_SPECS:
        assert f"ompi_tpu_{name}" in text
    # value lines parse as "<metric> <number>"
    for ln in lines:
        if not ln.startswith("#"):
            metric, val = ln.split()
            assert metric.startswith("ompi_tpu_")
            float(val)


# ---------------------------------------------------------------------------
# always-on counters (zero-copy vs pack, plan classes)
# ---------------------------------------------------------------------------

def test_commit_counts_plan_classes():
    before = dict(trace.counters)
    dt.FLOAT64.contiguous(4).commit()                      # single
    dt.INT32.vector(count=8, blocklength=2, stride=4).commit()   # strided
    dt.INT64.indexed([1, 1], [0, 5]).commit()              # runs
    d = dict(trace.counters)
    assert d["convertor_plan_single_total"] == \
        before["convertor_plan_single_total"] + 1
    assert d["convertor_plan_strided_total"] == \
        before["convertor_plan_strided_total"] + 1
    assert d["convertor_plan_runs_total"] == \
        before["convertor_plan_runs_total"] + 1


def test_recommit_does_not_double_count():
    before = trace.counters["convertor_plan_strided_total"]
    v = dt.INT32.vector(count=4, blocklength=1, stride=2).commit()
    v.commit()
    v.commit()
    assert trace.counters["convertor_plan_strided_total"] == before + 1


def test_zero_copy_vs_packed_send_counters():
    before_zc = trace.counters["pml_zero_copy_sends_total"]
    before_pk = trace.counters["pml_packed_sends_total"]

    def body(comm):
        peer = (comm.rank + 1) % comm.size
        # contiguous send: plan collapses → zero-copy view
        r = comm.irecv(source=(comm.rank - 1) % comm.size, tag=1)
        comm.send(np.arange(16, dtype=np.float64), dest=peer, tag=1)
        r.wait()
        # genuinely strided derived type → staged pack
        vec = dt.INT32.vector(count=4, blocklength=1, stride=2).commit()
        r = comm.irecv(np.empty(4, np.int32),
                       source=(comm.rank - 1) % comm.size, tag=2,
                       datatype=dt.INT32, count=4)
        comm.send(np.arange(8, dtype=np.int32), dest=peer, tag=2,
                  datatype=vec, count=1)
        r.wait()
        return True

    assert all(run_ranks(2, body))
    assert trace.counters["pml_zero_copy_sends_total"] >= before_zc + 2
    assert trace.counters["pml_packed_sends_total"] >= before_pk + 2


def test_counters_snapshot_carries_convertor_stats():
    snap = trace.counters_snapshot()
    for key in ("convertor_pack_calls_total", "convertor_unpack_calls_total",
                "pml_zero_copy_sends_total", "convertor_plan_single_total"):
        assert key in snap
    json.dumps(snap)          # one-line-record serializable


# ---------------------------------------------------------------------------
# pvar integration
# ---------------------------------------------------------------------------

def test_counters_readable_as_pvars():
    from ompi_tpu.mpi import mpit

    before = trace.counters["pml_zero_copy_sends_total"]
    pv = mpit.pvar_registry.lookup("pml_zero_copy_sends_total")
    assert pv.read() == before
    trace.count("pml_zero_copy_sends_total")
    assert pv.read() == before + 1
    trace.counters["pml_zero_copy_sends_total"] = before  # restore


def test_default_path_uses_tmpdir(monkeypatch):
    monkeypatch.setenv("TMPDIR", "/tmp/some-dir")
    assert trace.default_path(3, 1) == \
        "/tmp/some-dir/ompi_tpu_trace_3_rank1.json"
    assert os.path.basename(trace.default_path(0, 0)) == \
        "ompi_tpu_trace_0_rank0.json"
