"""MPI-IO tests — views, individual/collective/shared/ordered access.

≈ the reference's OMPIO coverage (file views via datatypes, two-phase
collective IO, shared file pointers) validated against plain-file ground
truth, the way test/datatype validates pack/unpack against memcpy.
"""

from __future__ import annotations

import numpy as np
import pytest

from ompi_tpu.core.config import var_registry
from ompi_tpu.mpi import datatype as dt
from ompi_tpu.mpi import io as mio
from ompi_tpu.mpi.constants import MPIException
from tests.mpi.harness import run_ranks


# ---------------------------------------------------------------------------
# FileView (pure mapping logic)
# ---------------------------------------------------------------------------

def test_view_contiguous_bytes():
    v = mio.FileView(disp=10)
    assert v.contiguous
    assert v.byte_runs(5, 7) == [(15, 7)]


def test_view_etype_units():
    v = mio.FileView(disp=0, etype=dt.FLOAT64)
    assert v.byte_runs(2, 16) == [(16, 16)]


def test_view_strided_filetype():
    """vector(2 blocks of 2 int32, stride 4) resized to 32B tiles the file:
    payload runs [0,8) and [16,24) per tile."""
    ft = dt.INT32.vector(2, 2, 4).commit()
    v = mio.FileView(disp=0, etype=dt.INT32, filetype=ft)
    assert not v.contiguous
    # first tile: 4 etypes → bytes 0-8 (run 1) and 16-24 (run 2)
    assert v.byte_runs(0, 8) == [(0, 8)]
    assert v.byte_runs(0, 16) == [(0, 8), (16, 8)]
    # natural extent is 24B, so tile 2's first run (24,8) merges with (16,8)
    assert v.byte_runs(2, 16) == [(16, 16)]
    # an explicit resize to 32B keeps the tiles apart
    v32 = mio.FileView(disp=0, etype=dt.INT32,
                       filetype=ft.resized(32).commit())
    assert v32.byte_runs(2, 16) == [(16, 8), (32, 8)]


def test_view_rejects_partial_etype():
    ft = dt.INT32.contiguous(3).commit()
    with pytest.raises(MPIException):
        mio.FileView(etype=dt.FLOAT64, filetype=ft)  # 12 % 8 != 0


# ---------------------------------------------------------------------------
# individual IO
# ---------------------------------------------------------------------------

def test_open_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "a.dat")

    def body(comm):
        f = mio.File.open(comm, path,
                          mio.MODE_RDWR | mio.MODE_CREATE)
        f.set_view(0, dt.FLOAT64)
        f.write_at(comm.rank * 4, np.full(4, float(comm.rank)))
        f.close()
        f2 = mio.File.open(comm, path)
        f2.set_view(0, dt.FLOAT64)
        out = f2.read_at(0, 4 * comm.size)
        f2.close()
        return out

    for out in run_ranks(3, body):
        want = np.repeat(np.arange(3.0), 4)
        np.testing.assert_array_equal(out, want)


def test_individual_pointer_and_seek(tmp_path):
    path = str(tmp_path / "b.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        f.set_view(0, dt.INT32)
        if comm.rank == 0:
            f.write(np.arange(10, dtype=np.int32))
            assert f.get_position() == 10
            f.seek(2)
            got = f.read(3)
            np.testing.assert_array_equal(got, [2, 3, 4])
            f.seek(-2, mio.SEEK_CUR)
            assert f.get_position() == 3
            f.seek(0, mio.SEEK_END)
            assert f.get_position() == 10
        comm.barrier()
        f.close()

    run_ranks(2, body)


def test_strided_view_write_read(tmp_path):
    """Each rank writes its own interleaved column through a strided view
    (the canonical MPI-IO pattern: rank r owns every size-th block)."""
    path = str(tmp_path / "c.dat")
    n = 3      # ranks
    bl = 2     # ints per block

    def body(comm):
        ft = dt.INT32.vector(1, bl, bl * n).resized(
            bl * n * 4).commit()
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        f.set_view(comm.rank * bl * 4, dt.INT32, ft)
        data = np.arange(4 * bl, dtype=np.int32) + 100 * comm.rank
        f.write_at(0, data)
        f.close()
        return None

    run_ranks(n, body)
    # ground truth: blocks interleave rank-major
    raw = np.fromfile(path, dtype=np.int32)
    want = []
    for blk in range(4):
        for r in range(n):
            want.extend(np.arange(blk * bl, blk * bl + bl) + 100 * r)
    np.testing.assert_array_equal(raw, np.array(want, dtype=np.int32))


def test_read_write_mode_guards(tmp_path):
    path = str(tmp_path / "d.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_CREATE | mio.MODE_WRONLY)
        try:
            f.read_at(0, 1)
        except MPIException:
            ok1 = True
        else:
            ok1 = False
        f.close()
        f2 = mio.File.open(comm, path, mio.MODE_RDONLY)
        try:
            f2.write_at(0, np.zeros(1, np.uint8))
        except MPIException:
            ok2 = True
        else:
            ok2 = False
        f2.close()
        return ok1 and ok2

    assert all(run_ranks(2, body))


def test_excl_create(tmp_path):
    path = str(tmp_path / "e.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_CREATE | mio.MODE_EXCL
                          | mio.MODE_RDWR)
        f.close()
        # second EXCL open must fail collectively
        try:
            mio.File.open(comm, path, mio.MODE_CREATE | mio.MODE_EXCL
                          | mio.MODE_RDWR)
        except MPIException:
            return True
        return False

    assert all(run_ranks(3, body))


def test_delete_on_close_and_set_size(tmp_path):
    import os

    path = str(tmp_path / "f.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_CREATE | mio.MODE_RDWR
                          | mio.MODE_DELETE_ON_CLOSE)
        f.set_size(128)
        assert f.get_size() == 128
        f.preallocate(64)            # grow-only: no shrink
        assert f.get_size() == 128
        f.close()
        return os.path.exists(path)

    assert not any(run_ranks(2, body))


# ---------------------------------------------------------------------------
# collective two-phase IO
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("twophase", [True, False])
def test_write_at_all_interleaved(tmp_path, twophase):
    path = str(tmp_path / f"g{twophase}.dat")
    var_registry.set("io_twophase", twophase)
    try:
        n = 4

        def body(comm):
            ft = dt.FLOAT32.vector(1, 2, 2 * n).resized(2 * n * 4).commit()
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            f.set_view(comm.rank * 8, dt.FLOAT32, ft)
            data = (np.arange(6, dtype=np.float32)
                    + 10 * comm.rank)
            f.write_at_all(0, data)
            out = f.read_at_all(0, 6)
            f.close()
            return out

        results = run_ranks(n, body)
        for r, out in enumerate(results):
            np.testing.assert_array_equal(
                out, np.arange(6, dtype=np.float32) + 10 * r)
        raw = np.fromfile(path, dtype=np.float32)
        want = []
        for blk in range(3):
            for r in range(n):
                want.extend(np.arange(blk * 2, blk * 2 + 2) + 10 * r)
        np.testing.assert_array_equal(raw, np.array(want, np.float32))
    finally:
        var_registry.set("io_twophase", True)


def test_write_all_with_pointer(tmp_path):
    path = str(tmp_path / "h.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        f.set_view(0, dt.INT64)
        f.seek(comm.rank * 3)
        f.write_all(np.arange(3, dtype=np.int64) + 100 * comm.rank)
        f.close()

    run_ranks(3, body)
    raw = np.fromfile(path, dtype=np.int64)
    want = np.concatenate([np.arange(3) + 100 * r for r in range(3)])
    np.testing.assert_array_equal(raw, want)


def test_collective_read_uneven(tmp_path):
    """Ranks request different, partially empty extents collectively."""
    path = str(tmp_path / "i.dat")
    base = np.arange(32, dtype=np.float64)
    base.tofile(path)

    def body(comm):
        f = mio.File.open(comm, path)
        f.set_view(0, dt.FLOAT64)
        count = [0, 5, 27][comm.rank]
        off = [0, 0, 5][comm.rank]
        out = f.read_at_all(off, count)
        f.close()
        return out

    r0, r1, r2 = run_ranks(3, body)
    assert len(r0) == 0
    np.testing.assert_array_equal(r1, base[:5])
    np.testing.assert_array_equal(r2, base[5:])


def test_collective_read_past_eof(tmp_path):
    """Short preads at EOF must not shift later runs' bytes into earlier
    requests (regression): ranks request beyond the end of the file and get
    exactly the available prefix."""
    path = str(tmp_path / "r.dat")
    base = np.arange(10, dtype=np.float64)
    base.tofile(path)

    def body(comm):
        f = mio.File.open(comm, path)
        f.set_view(0, dt.FLOAT64)
        # rank 0 asks for [0, 8), rank 1 for [8, 20) — 10 exist
        off = [0, 8][comm.rank]
        count = [8, 12][comm.rank]
        out = f.read_at_all(off, count)
        f.close()
        return out

    r0, r1 = run_ranks(2, body)
    np.testing.assert_array_equal(r0, base[:8])
    np.testing.assert_array_equal(r1, base[8:10])


# ---------------------------------------------------------------------------
# shared / ordered pointers
# ---------------------------------------------------------------------------

def test_write_shared_disjoint(tmp_path):
    path = str(tmp_path / "j.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        f.set_view(0, dt.INT32)
        f.write_shared(np.full(4, comm.rank, np.int32))
        comm.barrier()
        pos = f.get_position_shared()
        f.close()
        return pos

    results = run_ranks(4, body)
    assert all(p == 16 for p in results)
    raw = np.fromfile(path, dtype=np.int32)
    # every rank's block lands somewhere, intact and disjoint
    assert sorted(raw.reshape(4, 4)[:, 0]) == [0, 1, 2, 3]
    for row in raw.reshape(4, 4):
        assert (row == row[0]).all()


def test_write_ordered_rank_order(tmp_path):
    path = str(tmp_path / "k.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
        f.set_view(0, dt.INT32)
        f.write_ordered(np.full(2 + comm.rank, comm.rank, np.int32))
        out = None
        if comm.rank == 0:
            out = f.read_at(0, 2 + 3 + 4)
        f.close()
        return out

    results = run_ranks(3, body)
    want = np.array([0, 0, 1, 1, 1, 2, 2, 2, 2], np.int32)
    np.testing.assert_array_equal(results[0], want)


def test_read_ordered(tmp_path):
    path = str(tmp_path / "l.dat")
    np.arange(9, dtype=np.int32).tofile(path)

    def body(comm):
        f = mio.File.open(comm, path)
        f.set_view(0, dt.INT32)
        out = f.read_ordered(3)
        f.close()
        return out

    results = run_ranks(3, body)
    for r, out in enumerate(results):
        np.testing.assert_array_equal(out, np.arange(r * 3, r * 3 + 3))


def test_derived_etype_pointer_advance(tmp_path):
    """Pointers advance in *etype* units: a 2-int32 etype read of 2 etypes
    returns 4 base elements but moves the pointer by 2 (regression)."""
    path = str(tmp_path / "n.dat")
    np.arange(12, dtype=np.int32).tofile(path)

    def body(comm):
        et = dt.INT32.contiguous(2).commit()
        f = mio.File.open(comm, path)
        f.set_view(0, et)
        out = f.read(2)
        pos = f.get_position()
        out2 = f.read(1)
        f.close()
        return out, pos, out2

    out, pos, out2 = run_ranks(1, body)[0]
    np.testing.assert_array_equal(out, [0, 1, 2, 3])
    assert pos == 2
    np.testing.assert_array_equal(out2, [4, 5])


def test_seek_end_strided_view(tmp_path):
    """SEEK_END maps file size through the view: a 96-byte file with 8
    payload bytes per 24-byte tile holds 8 etypes, not 24 (regression)."""
    path = str(tmp_path / "o.dat")
    np.zeros(24, dtype=np.int32).tofile(path)  # 96 bytes

    def body(comm):
        ft = dt.INT32.vector(1, 2, 6).resized(24).commit()
        f = mio.File.open(comm, path)
        f.set_view(0, dt.INT32, ft)
        f.seek(0, mio.SEEK_END)
        pos = f.get_position()
        f.close()
        return pos

    assert run_ranks(1, body)[0] == 8


def test_append_starts_pointers_at_eof(tmp_path):
    """MODE_APPEND: individual AND shared pointers start at EOF, so the
    first write_shared appends instead of overwriting (regression)."""
    path = str(tmp_path / "p.dat")
    np.arange(4, dtype=np.uint8).tofile(path)

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_APPEND)
        assert f.get_position() == 4          # default byte view: EOF = 4
        assert f.get_position_shared() == 4
        f.write_shared(np.array([99], np.uint8))
        f.close()

    run_ranks(1, body)
    np.testing.assert_array_equal(np.fromfile(path, dtype=np.uint8),
                                  [0, 1, 2, 3, 99])


def test_failed_shared_access_does_not_advance(tmp_path):
    path = str(tmp_path / "q.dat")

    def body(comm):
        f = mio.File.open(comm, path, mio.MODE_CREATE | mio.MODE_WRONLY)
        f.set_view(0, dt.INT32)
        try:
            f.read_shared(5)
        except MPIException:
            pass
        pos = f.get_position_shared()
        try:
            f.seek_shared(3, whence=7)
        except MPIException:
            pass
        pos2 = f.get_position_shared()
        f.close()
        return pos, pos2

    assert run_ranks(1, body)[0] == (0, 0)


def test_seek_shared(tmp_path):
    path = str(tmp_path / "m.dat")
    np.arange(8, dtype=np.int64).tofile(path)

    def body(comm):
        f = mio.File.open(comm, path)
        f.set_view(0, dt.INT64)
        f.seek_shared(4)
        comm.barrier()
        assert f.get_position_shared() == 4
        f.close()

    run_ranks(2, body)

# ---------------------------------------------------------------------------
# sharedfp/individual (relaxed shared-pointer semantics, opt-in)
# ---------------------------------------------------------------------------

def _forced_individual():
    var_registry.set("io_sharedfp", "individual")


def _restore_sharedfp():
    var_registry.set("io_sharedfp", "")


def test_sharedfp_individual_merge_order(tmp_path):
    """Writes spool locally; the close-time merge lands them in global
    timestamp order (barriers between writes make the order exact)."""
    path = str(tmp_path / "ind.dat")
    _forced_individual()
    try:
        def body(comm):
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            f.set_view(0, dt.INT32)
            # two rounds, rank order enforced by barriers: the global
            # timestamp sort must reproduce exactly this interleaving
            for round_ in range(2):
                for r in range(comm.size):
                    if comm.rank == r:
                        f.write_shared(np.full(
                            2, 10 * round_ + r, np.int32))
                    comm.barrier()
            # nothing on disk yet: the spool is local until the merge
            before = os.path.getsize(path) if comm.rank == 0 else -1
            comm.barrier()
            f.close()
            return before

        import os

        sizes = run_ranks(3, body)
        assert sizes[0] == 0            # pre-merge: file still empty
        raw = np.fromfile(path, dtype=np.int32)
        want = []
        for round_ in range(2):
            for r in range(3):
                want.extend([10 * round_ + r] * 2)
        np.testing.assert_array_equal(raw, np.array(want, np.int32))
    finally:
        _restore_sharedfp()


def test_sharedfp_individual_reads_erroneous(tmp_path):
    path = str(tmp_path / "ind2.dat")
    _forced_individual()
    try:
        def body(comm):
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            errs = 0
            for fn in (lambda: f.read_shared(1),
                       lambda: f.seek_shared(0),
                       lambda: f.get_position_shared()):
                try:
                    fn()
                except MPIException:
                    errs += 1
            comm.barrier()
            f.close()
            return errs

        assert run_ranks(2, body) == [3, 3]
    finally:
        _restore_sharedfp()


def test_sharedfp_individual_ordered_after_shared(tmp_path):
    """write_ordered is collective: it first lands the pending spooled
    writes, then appends in rank order after them."""
    path = str(tmp_path / "ind3.dat")
    _forced_individual()
    try:
        def body(comm):
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            f.set_view(0, dt.INT32)
            for r in range(comm.size):    # deterministic global order
                if comm.rank == r:
                    f.write_shared(np.full(1, 100 + r, np.int32))
                comm.barrier()
            f.write_ordered(np.full(2, comm.rank, np.int32))
            out = f.read_at(0, comm.size + 2 * comm.size) \
                if comm.rank == 0 else None
            f.close()
            return out

        results = run_ranks(2, body)
        want = np.array([100, 101, 0, 0, 1, 1], np.int32)
        np.testing.assert_array_equal(results[0], want)
    finally:
        _restore_sharedfp()


def test_sharedfp_individual_sync_lands_pending(tmp_path):
    """An explicit (collective) sync materializes the spool without
    closing; later writes merge after the earlier ones."""
    path = str(tmp_path / "ind4.dat")
    _forced_individual()
    try:
        def body(comm):
            f = mio.File.open(comm, path, mio.MODE_RDWR | mio.MODE_CREATE)
            f.set_view(0, dt.INT32)
            f.write_shared(np.full(1, comm.rank, np.int32))
            comm.barrier()
            f.sync()
            mid = np.fromfile(path, dtype=np.int32).size \
                if comm.rank == 0 else -1
            comm.barrier()
            f.write_shared(np.full(1, 10 + comm.rank, np.int32))
            comm.barrier()
            f.close()
            return mid

        mids = run_ranks(2, body)
        assert mids[0] == 2             # first round landed at sync
        raw = np.fromfile(path, dtype=np.int32)
        assert raw.size == 4
        assert sorted(raw[:2]) == [0, 1]      # round 1 before round 2
        assert sorted(raw[2:]) == [10, 11]
    finally:
        _restore_sharedfp()
