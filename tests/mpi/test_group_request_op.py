"""Tests for Group set ops, Request completion, and the Op table."""

import threading
import time

import numpy as np
import pytest

from ompi_tpu.mpi import op as op_mod
from ompi_tpu.mpi.constants import UNDEFINED, MPIException
from ompi_tpu.mpi.group import Group
from ompi_tpu.mpi.request import Request, wait_all, wait_any


# -- groups ----------------------------------------------------------------

def test_group_basics():
    g = Group([4, 2, 7])
    assert g.size == 3
    assert g.rank_of(2) == 1
    assert g.rank_of(5) == UNDEFINED
    assert g.world_rank(2) == 7


def test_group_duplicates_rejected():
    with pytest.raises(MPIException):
        Group([1, 1])


def test_group_set_ops():
    a, b = Group([0, 1, 2, 3]), Group([2, 3, 4])
    assert a.union(b).ranks == (0, 1, 2, 3, 4)
    assert a.intersection(b).ranks == (2, 3)
    assert a.difference(b).ranks == (0, 1)


def test_group_incl_excl():
    g = Group([10, 11, 12, 13])
    assert g.incl([3, 0]).ranks == (13, 10)
    assert g.excl([1, 2]).ranks == (10, 13)
    with pytest.raises(MPIException):
        g.excl([9])


def test_translate_ranks():
    a, b = Group([5, 6, 7]), Group([7, 5])
    assert a.translate_ranks([0, 1, 2], b) == [1, UNDEFINED, 0]


def test_group_compare():
    assert Group([0, 1]).compare(Group([0, 1])) == "ident"
    assert Group([0, 1]).compare(Group([1, 0])) == "similar"
    assert Group([0, 1]).compare(Group([0, 2])) == "unequal"


# -- requests --------------------------------------------------------------

def test_request_complete_and_wait():
    r = Request()
    threading.Timer(0.05, lambda: r.complete("val")).start()
    assert r.wait(timeout=5) == "val"
    assert r.done() and r.test()


def test_request_fail_propagates():
    r = Request()
    r.fail(MPIException("boom", error_class=15))
    with pytest.raises(MPIException, match="boom"):
        r.wait()
    assert r.status.error == 15


def test_request_completes_once():
    r = Request()
    r.complete(1)
    r.complete(2)
    assert r.wait() == 1


def test_completion_callback_after_done():
    r = Request()
    r.complete("x")
    seen = []
    r.add_completion_callback(lambda req: seen.append(req))
    assert seen == [r]


def test_wait_all_collects_first_error():
    ok, bad = Request(), Request()
    ok.complete(1)
    bad.fail(MPIException("nope"))
    with pytest.raises(MPIException, match="nope"):
        wait_all([ok, bad])


def test_wait_any_returns_first():
    a, b = Request(), Request()
    threading.Timer(0.05, lambda: b.complete("b")).start()
    idx, val = wait_any([a, b], timeout=5)
    assert (idx, val) == (1, "b")


def test_wait_timeout():
    with pytest.raises(TimeoutError):
        Request().wait(timeout=0.05)


# -- ops -------------------------------------------------------------------

def test_basic_ops_host():
    a = np.array([1, 2, 3])
    b = np.array([4, 1, 3])
    assert (op_mod.SUM(a, b) == [5, 3, 6]).all()
    assert (op_mod.MAX(a, b) == [4, 2, 3]).all()
    assert (op_mod.BAND(a, b) == [0, 0, 3]).all()


def test_maxloc_tie_takes_lowest_loc():
    from ompi_tpu.mpi.datatype import FLOAT_INT

    x = np.zeros(2, FLOAT_INT.base_np)
    y = np.zeros(2, FLOAT_INT.base_np)
    x["val"], x["loc"] = [5.0, 1.0], [3, 0]
    y["val"], y["loc"] = [5.0, 2.0], [1, 1]
    out = op_mod.MAXLOC(x, y)
    assert out["loc"][0] == 1  # tie on val=5 → lower loc wins
    assert out["val"][1] == 2.0 and out["loc"][1] == 1


def test_device_op():
    import jax.numpy as jnp

    out = op_mod.SUM.device(jnp.ones(3), jnp.ones(3))
    np.testing.assert_array_equal(np.asarray(out), [2, 2, 2])


def test_maxloc_has_no_device_impl():
    with pytest.raises(MPIException):
        op_mod.MAXLOC.device(None, None)


def test_user_op():
    myop = op_mod.create_op(lambda a, b: a + 2 * b, commutative=False)
    assert (myop(np.array([1]), np.array([2])) == [5]).all()
    assert not myop.commutative
