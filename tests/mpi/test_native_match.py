"""The compiled matching engine (_native/fastdss.c Engine) vs the pure
python matcher: identical MPI semantics on both paths.

Every test here runs twice — native engine on (the default) and off
(pml_native_match=0) — so the fallback path keeps real coverage now that
the engine is what the suite normally exercises.  The engine-only tests
at the bottom poke the C object directly (ordering, hold/release,
reset) where the python path has no equivalent surface.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from ompi_tpu.core.config import var_registry
from ompi_tpu.mpi.constants import ANY_SOURCE, ANY_TAG, MPIException
from tests.mpi.harness import run_ranks


@pytest.fixture(params=[True, False], ids=["native", "python"])
def native(request):
    old = var_registry.get("pml_native_match")
    var_registry.set("pml_native_match", request.param)
    yield request.param
    var_registry.set("pml_native_match", old)


def _engine_active(comm) -> bool:
    return comm.pml._eng is not None


def test_engine_gate_matches_var(native):
    def body(comm):
        return _engine_active(comm)

    active = run_ranks(2, body)
    if native:
        # engine may legitimately be absent when the native build failed
        assert active[0] in (True, False)
    else:
        assert active == [False, False]


def test_unexpected_arrival_order(native):
    """Two sends queued unexpected; a wildcard recv takes the FIRST."""

    def body(comm):
        if comm.rank == 0:
            comm.send(np.array([1], np.int32), dest=1, tag=5)
            comm.send(np.array([2], np.int32), dest=1, tag=6)
            comm.recv(source=1, tag=9)
            return None
        comm.recv(source=0, tag=9, buf=None) \
            if False else None
        time.sleep(0.2)      # both frames land unexpected first
        a = comm.recv(source=ANY_SOURCE, tag=ANY_TAG)
        b = comm.recv(source=ANY_SOURCE, tag=ANY_TAG)
        comm.send(np.array([0], np.int32), dest=0, tag=9)
        return int(a[0]), int(b[0])

    out = run_ranks(2, body)
    assert out[1] == (1, 2)


def test_wildcards_and_specific_mix(native):
    def body(comm):
        if comm.rank == 0:
            for tag in (3, 4, 5):
                comm.send(np.array([tag], np.int64), dest=1, tag=tag)
            return None
        time.sleep(0.2)
        four = comm.recv(source=0, tag=4)       # specific steals tag 4
        rest = sorted(int(comm.recv(source=ANY_SOURCE, tag=ANY_TAG)[0])
                      for _ in range(2))
        return int(four[0]), rest

    out = run_ranks(2, body)
    assert out[1] == (4, [3, 5])


def test_posted_buffer_delivery_and_status(native):
    """The fast lane's 'done' action must fill status exactly like the
    python _deliver."""
    from ompi_tpu.mpi.request import Status

    def body(comm):
        if comm.rank == 0:
            comm.send(np.arange(6, dtype=np.float32), dest=1, tag=2)
            return None
        buf = np.zeros(6, np.float32)
        st = Status()
        comm.recv(buf=buf, source=0, tag=2, status=st)
        return buf.tolist(), st.source, st.tag, st.count

    out = run_ranks(2, body)
    vals, src, tag, count = out[1]
    assert vals == [0, 1, 2, 3, 4, 5]
    assert (src, tag, count) == (0, 2, 6)


def test_truncation_error_both_paths(native):
    """Payload larger than the posted count must raise ERR_TRUNCATE —
    the fast lane is required to fall back so the error still fires."""

    def body(comm):
        if comm.rank == 0:
            comm.send(np.arange(8, dtype=np.int32), dest=1, tag=1)
            return None
        buf = np.zeros(4, np.int32)
        try:
            comm.recv(buf=buf, source=0, tag=1, count=4)
            return "no error"
        except MPIException as e:
            return "truncated" if "truncat" in str(e) else str(e)

    assert run_ranks(2, body)[1] == "truncated"


def test_cancel_posted_recv(native):
    def body(comm):
        if comm.rank == 0:
            req = comm.irecv(source=1, tag=77)
            req.cancel()
            assert req.done()
            # a cancelled recv must not steal a later frame
            comm.send(np.array([5], np.int64), dest=1, tag=8)
        else:
            got = comm.recv(source=0, tag=8)
            assert int(got[0]) == 5
        comm.barrier()
        return True

    assert run_ranks(2, body) == [True, True]


def test_mprobe_detach_under_engine(native):
    def body(comm):
        if comm.rank == 0:
            comm.send(np.array([3, 1, 4], np.int32), dest=1, tag=6)
            return None
        msg, st = comm.mprobe(source=0, tag=6)
        assert st.count == 3
        # a wildcard recv CANNOT see the detached message
        assert comm.iprobe(source=ANY_SOURCE, tag=ANY_TAG) is None
        out = comm.mrecv(message=msg)
        return out.tolist()

    assert run_ranks(2, body)[1] == [3, 1, 4]


def test_listeners_with_fast_lane(native):
    """Monitoring attached: the engine paths must still emit balanced
    match/deliver events (the fast lane re-routes or emits them)."""

    def body(comm):
        events = []

        def listener(e, info):
            events.append(e)

        comm.pml.add_listener(listener)
        try:
            if comm.rank == 0:
                comm.send(np.array([1], np.int32), dest=1, tag=3)
                comm.recv(source=1, tag=4)
            else:
                comm.recv(source=0, tag=3)
                comm.send(np.array([2], np.int32), dest=0, tag=4)
        finally:
            comm.pml.remove_listener(listener)
        return events

    out = run_ranks(2, body)
    for events in out:
        assert "send_post" in events
        assert "recv_post" in events
        assert "deliver" in events


def test_shm_two_process_roundtrip(native):
    """Deployment shape: two real processes over the shm BTL — the
    fused-drain + receiver-pull path end to end."""
    import multiprocessing as mp

    from ompi_tpu.mpi.comm import Communicator
    from ompi_tpu.mpi.group import Group
    from ompi_tpu.mpi.pml import PmlOb1

    def child(c2p, p2c, flag):
        var_registry.set("pml_native_match", flag)
        pml = PmlOb1(1)
        c2p.put(pml.address)
        peers = p2c.get()
        pml.set_peers(peers)
        comm = Communicator(Group(range(2)), cid=0, pml=pml,
                            my_world_rank=1)
        buf = np.zeros(16, np.int32)
        for _ in range(50):
            comm.recv(buf=buf, source=0, tag=1)
            buf += 1
            comm.send(buf, dest=0, tag=1)
        pml.close()

    ctx = mp.get_context("fork")
    c2p, p2c = ctx.Queue(), ctx.Queue()
    proc = ctx.Process(target=child, args=(c2p, p2c, native), daemon=True)
    proc.start()
    pml = PmlOb1(0)
    try:
        peers = {0: pml.address, 1: c2p.get(timeout=30)}
        p2c.put(peers)
        pml.set_peers(peers)
        comm = Communicator(Group(range(2)), cid=0, pml=pml,
                            my_world_rank=0)
        msg = np.zeros(16, np.int32)
        for i in range(50):
            comm.send(msg, dest=1, tag=1)
            msg = comm.recv(source=1, tag=1)
        assert (np.asarray(msg) == 50).all()
        proc.join(timeout=10)
        assert proc.exitcode == 0
    finally:
        pml.close()


# -- direct engine pokes (native only) ---------------------------------


def _engine():
    from ompi_tpu import _native

    fast = _native.fastdss()
    if fast is None or not hasattr(fast, "Engine"):
        pytest.skip("native engine unavailable")
    return fast.Engine()


def test_engine_out_of_order_hold_release():
    e = _engine()
    acts = e.incoming(3, {"t": "eager", "tag": 1, "cid": 0, "seq": 2},
                      b"c")
    assert acts == []                      # held
    acts = e.incoming(3, {"t": "eager", "tag": 1, "cid": 0, "seq": 0},
                      b"a")
    assert [a[0] for a in acts] == ["unexpected"]
    acts = e.incoming(3, {"t": "eager", "tag": 1, "cid": 0, "seq": 1},
                      b"b")
    # seq 1 releases the held seq 2 in order
    assert [a[0] for a in acts] == ["unexpected", "unexpected"]
    hits = [e.improbe(0, 3, 1) for _ in range(3)]
    assert [bytes(h[2]) for h in hits] == [b"a", b"b", b"c"]


def test_engine_reset_peer_clears_gate():
    e = _engine()
    e.incoming(7, {"t": "eager", "tag": 1, "cid": 0, "seq": 0}, b"x")
    e.incoming(7, {"t": "eager", "tag": 1, "cid": 0, "seq": 5}, b"held")
    e.reset_peer(7)
    acts = e.incoming(7, {"t": "eager", "tag": 1, "cid": 0, "seq": 0},
                      b"fresh")
    assert [a[0] for a in acts] == ["unexpected"]
    # the pre-reset held frame must NOT leak out after the reset
    acts = e.incoming(7, {"t": "eager", "tag": 1, "cid": 0, "seq": 1},
                      b"next")
    assert len(acts) == 1


def test_engine_reserved_tag_guard():
    e = _engine()
    e.incoming(2, {"t": "eager", "tag": -9, "cid": 0, "seq": 0}, b"ctl")
    assert e.iprobe(0, ANY_SOURCE, ANY_TAG) is None
    assert e.iprobe(0, 2, -9) is not None


def test_engine_fast_lane_unexpected_then_post():
    e = _engine()
    acts = e.incoming_fast(4, 2, 0, 0, b"\x01\x00\x00\x00", "<i4", 1,
                           (1,))
    assert [a[0] for a in acts] == ["unexpected"]

    class R:
        pass

    hit = e.post(0, 4, 2, R(), None, 4, -1)
    assert hit is not None and bytes(hit[2]) == b"\x01\x00\x00\x00"
    assert hit[1]["elems"] == 1 and hit[1]["dt"] == "<i4"


def test_engine_drain_commits_before_bad_frame():
    """Mid-batch failure atomicity: frames decoded before a corrupt one
    keep their actions and tail positions; the NEXT drain call faces
    the corrupt frame first and raises cleanly (regression: a mid-batch
    error used to discard committed actions — completed-in-C recvs
    would hang)."""
    import struct

    from ompi_tpu import _native

    fast = _native.fastdss()
    if fast is None or not hasattr(fast, "Engine"):
        pytest.skip("native engine unavailable")
    e = fast.Engine()
    cap = 1 << 12
    mm = bytearray(64 + cap)
    struct.pack_into("<Q", mm, 16, cap)       # capacity
    struct.pack_into("<I", mm, 24, 0x53484D31)
    head, _ = fast.ring_send(
        mm, 0, {"t": "eager", "tag": 1, "cid": 0, "seq": 0,
                "dt": "<i4", "elems": 1, "shp": [1]},
        b"\x2a\x00\x00\x00")
    # a corrupt frame right behind it: bogus lens
    struct.pack_into("<II", mm, 64 + (head % cap), 0xFFFFFF, 5)
    struct.pack_into("<Q", mm, 0, head + 8 + 16)   # head past garbage

    new_tail, n, acts = e.drain_ring(9, mm, 0, 64)
    assert n == 1 and new_tail == head      # good frame committed...
    assert [a[0] for a in acts] == ["unexpected"]
    with pytest.raises(ValueError):          # ...bad one raises CLEAN
        e.drain_ring(9, mm, new_tail, 64)
