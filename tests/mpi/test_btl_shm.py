"""btl/shm: SPSC ring mechanics, endpoint routing, MCA gating
(≈ the role btl/vader plays in the reference; vader's unit coverage is
indirect — here the ring is tested directly plus end-to-end)."""

import os
import tempfile
import threading
import time

import numpy as np
import pytest

from ompi_tpu.core.config import var_registry
from ompi_tpu.mpi.btl import BtlEndpoint, btl_framework
from ompi_tpu.mpi.btl_shm import (FrameTooBig, ShmBTL, ShmRingReader,
                                  ShmRingWriter)
from tests.mpi.harness import run_ranks


def _mk_pair(capacity=1 << 16):
    inbox = tempfile.mkdtemp(prefix="shmtest-")
    w = ShmRingWriter(inbox, my_id=3, capacity=capacity)
    r = ShmRingReader(os.path.join(inbox, "ring_3"), peer=3)
    return w, r, inbox


def test_ring_roundtrip_and_unlink():
    w, r, inbox = _mk_pair()
    w.send({"tag": 7}, b"hello world")
    got = []
    n = r.poll(lambda peer, hdr, payload: got.append((peer, hdr, payload)))
    assert n == 1
    assert got == [(3, {"tag": 7}, b"hello world")]
    # the reader unlinked the ring file (crash-safe cleanup)
    assert os.listdir(inbox) == []
    w.close(); r.close(); os.rmdir(inbox)


def test_ring_wraparound_many_frames():
    w, r, inbox = _mk_pair(capacity=4096)
    got = []
    cb = lambda p, h, pl: got.append((h["i"], pl))
    for i in range(200):                     # far more bytes than capacity
        payload = bytes([i % 251]) * (i % 97)
        w.send({"i": i}, payload)
        r.poll(cb)
    while r.poll(cb):
        pass
    assert [i for i, _ in got] == list(range(200))
    for i, pl in got:
        assert pl == bytes([i % 251]) * (i % 97)
    w.close(); r.close(); os.rmdir(inbox)


def test_ring_backpressure_blocks_until_drained():
    w, r, inbox = _mk_pair(capacity=4096)
    done = threading.Event()

    def producer():
        for i in range(50):
            w.send({"i": i}, b"x" * 300)     # ~16KB total vs 4KB ring
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    got = []
    deadline = time.time() + 10
    while len(got) < 50 and time.time() < deadline:
        r.poll(lambda p, h, pl: got.append(h["i"]))
    t.join(timeout=5)
    assert done.is_set() and got == list(range(50))
    w.close(); r.close(); os.rmdir(inbox)


def test_frame_too_big_raises():
    w, r, inbox = _mk_pair(capacity=4096)
    with pytest.raises(FrameTooBig):
        w.send({}, b"y" * 3000)              # > capacity/2
    w.close(); r.close(); os.rmdir(inbox)


def test_shm_btl_end_to_end_discovery():
    frames = []
    rx = ShmBTL(0, lambda p, h, pl: frames.append((p, h, pl)))
    tx = ShmBTL(1, lambda p, h, pl: None)
    try:
        assert tx.connect(0, rx.address)
        tx.send(0, {"t": "probe"}, b"data")
        deadline = time.time() + 5
        while not frames and time.time() < deadline:
            time.sleep(0.01)
        assert frames == [(1, {"t": "probe"}, b"data")]
    finally:
        tx.close(); rx.close()


def test_shm_unreachable_card_falls_back():
    tx = ShmBTL(1, lambda p, h, pl: None)
    try:
        assert not tx.connect(0, "otherhost|/nonexistent/dir")
        assert not tx.connect(0, f"{tx.hostname}|/nonexistent/dir")
    finally:
        tx.close()


def test_endpoint_gating_mca_caret_shm():
    old = var_registry.get("btl_")
    try:
        var_registry.set("btl_", "^shm")
        ep = BtlEndpoint(0, lambda p, h, pl: None)
        assert ep.shm_btl is None
        assert ";shm=" not in ep.address
        ep.close()
        var_registry.set("btl_", "")
        ep2 = BtlEndpoint(0, lambda p, h, pl: None)
        assert ep2.shm_btl is not None
        assert ";shm=" in ep2.address
        ep2.close()
    finally:
        var_registry.set("btl_", old or "")


def test_p2p_rides_shm_same_host():
    """In-process ranks share the host: frames must move over shm rings,
    not TCP loopback (observable via the tcp out-socket table)."""
    def fn(comm):
        peer = (comm.rank + 1) % comm.size
        sreq = comm.isend(np.arange(100, dtype=np.int64) + comm.rank, peer,
                          tag=5)
        out = comm.recv(source=(comm.rank - 1) % comm.size, tag=5)
        sreq.wait()
        ep = comm.pml.endpoint
        used_tcp = len(ep.tcp_btl._out) > 0
        return out.tolist()[0], used_tcp

    res = run_ranks(3, fn)
    for r, (first, used_tcp) in enumerate(res):
        assert first == (r - 1) % 3
        assert not used_tcp, "frames leaked onto TCP despite shm"


def test_large_rndv_through_shm_fragments():
    """A rendezvous-size message (> eager limit) pipelines through the
    rings (or falls back per-frame safely) and arrives intact."""
    def fn(comm):
        n = 1 << 18                          # 2MB of float64 > eager limit
        if comm.rank == 0:
            data = np.arange(n, dtype=np.float64)
            comm.send(data, 1, tag=9)
            return True
        out = comm.recv(source=0, tag=9)
        return bool(np.array_equal(out, np.arange(n, dtype=np.float64)))

    assert run_ranks(2, fn) == [True, True]


def test_dead_receiver_detected_not_silently_lost():
    """A ring whose receiver pid is gone must raise PeerDeadError instead
    of accepting writes into the orphaned mapping (the respawn/retransmit
    path depends on the failure being VISIBLE)."""
    from ompi_tpu.mpi.btl_shm import PeerDeadError, ShmBTL

    a = ShmBTL(0, lambda *x: None)
    b = ShmBTL(1, lambda *x: None)
    try:
        # forge b's card with a pid that cannot exist
        host, inbox, _ = b.address.split("|")
        dead_card = f"{host}|{inbox}|{2**22 + 12345}"
        assert a.connect(1, dead_card)
        with pytest.raises(PeerDeadError):
            a.send(1, {"t": "eager", "seq": 0}, b"x")
        with pytest.raises(PeerDeadError):
            a.try_send(1, {"t": "eager", "seq": 1}, b"y")
        # a live pid (ours) passes
        a.drop_peer(1)
        live_card = f"{host}|{inbox}|{__import__('os').getpid()}"
        assert a.connect(1, live_card)
        a.send(1, {"t": "eager", "seq": 0}, b"x")
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# pid-liveness probe (shared cache: send path + coll/shm arena waits)
# ---------------------------------------------------------------------------

def test_probe_alive_answers_from_card_pid():
    import subprocess
    import sys as _sys

    btl = ShmBTL(0, lambda *a: None)
    try:
        inbox = tempfile.mkdtemp(prefix="shmprobe-")
        # a pid that is definitely dead (reaped child)
        p = subprocess.Popen([_sys.executable, "-c", "pass"])
        p.wait()
        dead_card = f"{btl.hostname}|{inbox}|{p.pid}"
        assert btl.probe_alive(7, dead_card) is False
        # a pid that is definitely alive (this test's process, via card)
        live_card = f"{btl.hostname}|{inbox}|{os.getppid() or os.getpid()}"
        assert btl.probe_alive(8, live_card) is True
        # unknowable: no card, never connected
        assert btl.probe_alive(9) is None
        # wrong host: the pid namespace would alias — unknowable
        other = f"not-{btl.hostname}|{inbox}|{p.pid}"
        assert btl.probe_alive(10, other) is None
        os.rmdir(inbox)
    finally:
        btl.close()


def test_probe_cache_is_shared_with_send_path():
    """_check_alive and probe_alive must consult ONE rate-limit cache —
    a fresh True answer suppresses the syscall for ~50ms on both."""
    btl = ShmBTL(0, lambda *a: None)
    try:
        btl._peer_pid[5] = os.getppid() or os.getpid()
        assert btl.probe_alive(5) is True
        t = btl._alive_until.get(5)
        assert t is not None
        btl._check_alive(5)             # within the window: no new stamp
        assert btl._alive_until.get(5) == t
    finally:
        btl.close()
