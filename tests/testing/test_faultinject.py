"""faultinject: plan grammar, deterministic verdicts, injector wiring."""

import pytest

from ompi_tpu.testing import faultinject as fi


def test_plan_grammar_parses_every_action():
    acts = fi.parse_plan(
        "rank=2:kill@step=3;rank=1:kill@t=0.5;daemon=1:kill@t=1.0;"
        "drop=0.01;drop=0.05@all;rank=1:drop=0.1;delay=0.02,5;dup=0.01")
    kinds = [a.kind for a in acts]
    assert kinds == ["kill", "kill", "daemon_kill", "drop", "drop",
                     "drop", "delay", "dup"]
    assert acts[0].rank == 2 and acts[0].at_step == 3
    assert acts[1].at_time == 0.5
    assert acts[2].vpid == 1 and acts[2].at_time == 1.0
    assert acts[3].scope == "ft" and acts[3].prob == 0.01
    assert acts[4].scope == "all"
    assert acts[5].rank == 1
    assert acts[6].delay_ms == 5.0
    assert acts[7].scope == "all"


@pytest.mark.parametrize("bad", [
    "kill",                      # no trigger
    "rank=1:kill@never=3",       # unknown trigger
    "drop=0.1@sometimes",        # unknown scope
    "frobnicate=1",              # unknown token
])
def test_plan_grammar_rejects_garbage(bad):
    with pytest.raises(ValueError):
        fi.parse_plan(bad)


def test_empty_plan_means_inactive():
    assert fi.parse_plan("") == []
    assert not fi.active() or fi.plan_text()  # env may arm it externally


def test_reg_trigger_parses_with_after_grace():
    """daemon=V:kill@reg=N[:after=S] — the ranks-registered barrier
    schedule (the midtree-kill de-flake)."""
    a = fi.parse_plan("daemon=1:kill@reg=4:after=1.5")[0]
    assert (a.kind, a.vpid, a.at_reg, a.after) == \
        ("daemon_kill", 1, 4, 1.5)
    assert a.at_time is None and a.at_step is None
    # field order within the entry is free; after defaults to 1.0
    b = fi.parse_plan("kill@reg=3:daemon=2")[0]
    assert (b.kind, b.vpid, b.at_reg, b.after) == \
        ("daemon_kill", 2, 3, 1.0)


@pytest.mark.parametrize("bad", [
    "rank=1:kill@reg=2",          # @reg is daemon-kill only
    "rank=0:hang@reg=2",          # hangs target ranks, no reg barrier
    "daemon=1:kill@reg=4:after=-1",   # negative grace
])
def test_reg_trigger_rejects_non_daemon_targets(bad):
    with pytest.raises(ValueError):
        fi.parse_plan(bad)


def test_arm_daemon_launch_waits_for_reg_and_ready_barrier(monkeypatch):
    """The reg watcher fires the kill only once BOTH counts cleared:
    every rank registered AND sent its init-complete notice —
    registration alone still leaves a window inside init (the modex
    fence and the first barrier can take seconds on a loaded box)."""
    import time as _time

    from ompi_tpu.runtime import pmix

    server = pmix.PMIxServer(size=2)
    killed = []
    monkeypatch.setattr(fi, "_daemon_die", lambda vpid: killed.append(vpid))
    monkeypatch.setenv(fi.ENV_PLAN, "daemon=1:kill@reg=2:after=0.0")
    try:
        fi.arm_daemon_launch(1, {pmix.ENV_URI: server.uri})
        _time.sleep(0.6)
        assert killed == [], "kill fired before anyone registered"
        c0 = pmix.PMIxClient(uri=server.uri, rank=0, size=2)
        c1 = pmix.PMIxClient(uri=server.uri, rank=1, size=2)
        assert pmix.query_regstate(server.uri) == (2, 0, 0)
        _time.sleep(0.6)
        assert killed == [], \
            "kill fired between registration and init completion"
        c0.ready()
        _time.sleep(0.4)
        assert killed == [], "kill fired with only 1/2 ranks ready"
        c1.ready()
        assert pmix.query_regstate(server.uri)[2] == 2
        deadline = _time.monotonic() + 10.0
        while not killed and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert killed == [1], "kill never fired after both barriers"
        c0.finalize()
        c1.finalize()
    finally:
        server.close()


def test_arm_daemon_launch_ignores_other_vpids_and_triggers(monkeypatch):
    monkeypatch.setattr(fi, "_daemon_die",
                        lambda vpid: pytest.fail("must not fire"))
    monkeypatch.setenv(fi.ENV_PLAN, "daemon=1:kill@reg=2")
    # wrong vpid: nothing armed; missing URI: nothing armed
    fi.arm_daemon_launch(2, {"OMPI_TPU_HNP_URI": "tcp://127.0.0.1:1"})
    fi.arm_daemon_launch(1, {})
    # legacy @t entries are arm_daemon's job, not the launch hook's
    monkeypatch.setenv(fi.ENV_PLAN, "daemon=1:kill@t=0.01")
    fi.arm_daemon_launch(1, {"OMPI_TPU_HNP_URI": "tcp://127.0.0.1:1"})
    import time as _time

    _time.sleep(0.3)


def test_verdict_is_pure_function_of_frame_identity():
    hdr = {"t": "ft", "op": "agree_c", "cid": 0, "aseq": 1, "n": 2}
    ident = fi._frame_ident(hdr)
    u1 = fi._u01(7, 0, 3, ident, "drop")
    u2 = fi._u01(7, 0, 3, ident, "drop")
    assert u1 == u2
    # a different attempt (retransmission) draws a fresh verdict
    hdr2 = dict(hdr, n=3)
    assert fi._frame_ident(hdr2) != ident
    # and a different seed moves the whole stream
    assert fi._u01(8, 0, 3, ident, "drop") != u1


def test_injector_respects_rank_scoping():
    acts = fi.parse_plan("rank=1:drop=1.0")
    inj0 = fi.Injector(0, acts, seed=0)
    inj1 = fi.Injector(1, acts, seed=0)
    hdr = {"t": "ft", "op": "revoke", "cid": 5, "n": 0}
    assert inj0.on_frame(2, hdr) == "send"     # action scoped to rank 1
    assert inj1.on_frame(2, hdr) == "drop"     # p=1.0 always drops
    assert inj1.events and inj1.events[0]["kind"] == "drop"


def test_drop_scope_ft_spares_data_frames():
    acts = fi.parse_plan("drop=1.0")           # default scope: ft only
    inj = fi.Injector(0, acts, seed=0)
    assert inj.on_frame(1, {"t": "eager", "tag": 3, "cid": 0,
                            "seq": 0}) == "send"
    assert inj.on_frame(1, {"t": "ft", "op": "revoke", "cid": 0,
                            "n": 0}) == "drop"


def test_drop_scope_all_hits_data_frames():
    acts = fi.parse_plan("drop=1.0@all")
    inj = fi.Injector(0, acts, seed=0)
    assert inj.on_frame(1, {"t": "eager", "tag": 3, "cid": 0,
                            "seq": 0}) == "drop"


def test_delay_verdict_carries_milliseconds():
    acts = fi.parse_plan("delay=1.0,7")
    inj = fi.Injector(0, acts, seed=0)
    verdict = inj.on_frame(1, {"t": "eager", "tag": 0, "cid": 0, "seq": 0})
    assert verdict == ("delay", 7.0)


def test_step_counter_advances_without_kills():
    inj = fi.Injector(0, fi.parse_plan("rank=5:kill@step=1"), seed=0)
    assert inj.step() == 0
    assert inj.step() == 1   # rank-scoped elsewhere: we survive
    assert inj.step() == 2


def test_kills_disabled_for_respawned_incarnations(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_RESTART", "1")
    inj = fi.Injector(0, fi.parse_plan("rank=0:kill@step=0"), seed=0)
    inj.step()   # would os._exit(9) if the first-life gate were missing
    assert inj.events == []


def test_btl_endpoint_arms_injector_under_plan():
    from ompi_tpu.core.config import var_registry
    from ompi_tpu.mpi.pml import PmlOb1

    fi.reset()
    var_registry.set("faultinject_plan", "drop=0.5")
    try:
        pml = PmlOb1(0)
        try:
            assert pml.endpoint._fault is not None
            assert pml.endpoint._fault.rank == 0
        finally:
            pml.close()
    finally:
        var_registry.set("faultinject_plan", "")
        fi.reset()


def test_hang_grammar_parses_step_and_time_triggers():
    acts = fi.parse_plan("rank=2:hang@step=3;rank=1:hang@t=0.5")
    assert [(a.kind, a.rank, a.at_step, a.at_time) for a in acts] == \
        [("hang", 2, 3, None), ("hang", 1, None, 0.5)]


def test_hang_rejects_daemons_and_missing_trigger():
    import pytest

    with pytest.raises(ValueError):
        fi.parse_plan("daemon=1:hang@t=1.0")   # daemons hang via heartbeats
    with pytest.raises(ValueError):
        fi.parse_plan("rank=1:hang")           # no trigger


def test_hang_fires_at_step_and_records_event(monkeypatch):
    hung = []
    monkeypatch.setattr(fi.Injector, "_hang_impl",
                        lambda self: hung.append(self.rank))
    acts = fi.parse_plan("rank=0:hang@step=2")
    inj = fi.Injector(0, acts, seed=0)
    inj.step(); inj.step()
    assert hung == []
    inj.step()                                 # entering step 2
    assert hung == [0]
    evs = [e for e in inj.events if e["kind"] == "hang"]
    assert evs and evs[0]["trigger"] == "step" and evs[0]["value"] == 2
    assert evs[0]["mode"] in ("stop", "spin")
    # one terminal fault per life: the next step must not re-fire
    inj.step()
    assert hung == [0]


def test_hang_first_life_only(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_RESTART", "1")
    acts = fi.parse_plan("rank=0:hang@step=0")
    inj = fi.Injector(0, acts, seed=0)
    inj.step()                                 # would fire in life 0
    assert not [e for e in inj.events if e["kind"] == "hang"]


def test_crash_grammar_parses_step_and_time_triggers():
    acts = fi.parse_plan("rank=2:crash@step=3;rank=1:crash@t=0.5")
    assert [(a.kind, a.rank, a.at_step, a.at_time) for a in acts] == \
        [("crash", 2, 3, None), ("crash", 1, None, 0.5)]


def test_crash_rejects_daemons_and_missing_trigger():
    import pytest

    with pytest.raises(ValueError):
        fi.parse_plan("daemon=1:crash@t=1.0")  # daemon revival doesn't exist
    with pytest.raises(ValueError):
        fi.parse_plan("rank=1:crash")          # no trigger


def test_crash_fires_in_every_life(monkeypatch):
    """Unlike kill/hang (first-life-only by design), crash re-arms in a
    respawned incarnation — the crash loop that proves the errmgr revive
    budget and the selfheal escalation ladder."""
    died = []
    monkeypatch.setattr(
        fi.Injector, "_fire_kill",
        lambda self, trigger, value, kind="kill":
            (died.append((self.rank, kind, trigger, value)),
             self._record(kind, trigger=trigger, value=value))[0])
    monkeypatch.setenv("OMPI_TPU_RESTART", "2")   # third life
    acts = fi.parse_plan("rank=0:crash@step=1;rank=0:kill@step=1")
    inj = fi.Injector(0, acts, seed=0)
    assert [a.kind for a in inj._kills] == ["crash"]  # kill stays gated
    inj.step(); inj.step()
    assert died == [(0, "crash", "step", 1)]
    evs = [e["kind"] for e in inj.events]
    assert evs == ["crash"]                     # distinct kind in the log


# ---------------------------------------------------------------------------
# collective triggers (stall/mismatch@coll — the hang-doctor chaos arm)
# ---------------------------------------------------------------------------

def test_stall_mismatch_grammar_parses_coll_trigger():
    acts = fi.parse_plan("rank=2:stall@coll=5;rank=1:mismatch@coll=3")
    assert [(a.kind, a.rank, a.at_coll) for a in acts] == \
        [("stall", 2, 5), ("mismatch", 1, 3)]


@pytest.mark.parametrize("bad", [
    "daemon=1:stall@coll=2",     # collective triggers target ranks
    "rank=1:stall",              # no trigger
    "rank=1:stall@step=2",       # @coll is the only stall trigger
    "rank=1:mismatch@t=1.0",     # same for mismatch
    "rank=1:hang@coll=2",        # a hang inside dispatch is spelled stall
    "rank=1:crash@coll=2",       # crash is every-life; @coll is first-only
    "kill@coll=2:daemon=1",      # daemon seen after the kill key: still
    #                              a daemon kill, and @coll targets ranks
])
def test_stall_mismatch_reject_bad_entries(bad):
    with pytest.raises(ValueError):
        fi.parse_plan(bad)


def test_kill_at_coll_grammar_and_first_life_only(monkeypatch):
    """kill@coll=N parses (the selfheal-coll mid-collective death) and
    arms the collective choke point in the FIRST life only — a revived
    victim must not re-die at the same ordinal."""
    acts = fi.parse_plan("rank=2:kill@coll=5")
    assert [(a.kind, a.rank, a.at_coll) for a in acts] == [("kill", 2, 5)]
    inj = fi.Injector(2, acts, seed=0)
    assert inj.coll_faults()
    for n in range(5):
        assert inj.coll_op() == (None, n)
    assert inj.coll_op() == ("kill", 5)
    # the revived life (OMPI_TPU_RESTART set) never arms it
    monkeypatch.setenv("OMPI_TPU_RESTART", "1")
    revived = fi.Injector(2, acts, seed=0)
    assert not revived.coll_faults()


def test_fire_coll_kill_exits_via_fire_kill(monkeypatch):
    """fire_coll('kill', ...) routes through _fire_kill (records the
    fault with trigger=coll, then os._exit in production)."""
    fired = []
    monkeypatch.setattr(
        fi.Injector, "_fire_kill",
        lambda self, trigger, value, kind="kill":
        fired.append((kind, trigger, value)))
    inj = fi.Injector(1, fi.parse_plan("rank=1:kill@coll=2"), seed=0)
    assert inj.coll_op() == (None, 0)
    assert inj.coll_op() == (None, 1)
    kind, n = inj.coll_op()
    assert (kind, n) == ("kill", 2)
    inj.fire_coll(kind, n, seq=7)
    assert fired == [("kill", "coll", 2)]


def test_coll_op_advances_ordinal_and_fires_by_position():
    inj = fi.Injector(1, fi.parse_plan("rank=1:stall@coll=2"), seed=0)
    assert inj.coll_faults()
    assert inj.coll_op() == (None, 0)
    assert inj.coll_op() == (None, 1)
    assert inj.coll_op() == ("stall", 2)
    other = fi.Injector(2, fi.parse_plan("rank=1:stall@coll=0"), seed=0)
    assert not other.coll_faults()


def test_coll_triggers_first_life_only(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_RESTART", "2")
    inj = fi.Injector(1, fi.parse_plan("rank=1:mismatch@coll=0"), seed=0)
    assert not inj.coll_faults() and inj.coll_op() == (None, 0)


def test_fire_coll_records_then_spin_parks(monkeypatch):
    """mismatch ALWAYS spin-parks (the divergent rank must stay
    capturable); the event carries the ordinal + op_seq so replay
    checks reproduce the schedule."""
    class _Break(Exception):
        pass

    def no_sleep(_s):
        raise _Break()

    monkeypatch.setattr(fi.time, "sleep", no_sleep)
    inj = fi.Injector(1, fi.parse_plan("rank=1:mismatch@coll=4"), seed=0)
    with pytest.raises(_Break):
        inj.fire_coll("mismatch", 4, 7)
    ev = inj.events[0]
    assert (ev["kind"], ev["trigger"], ev["value"], ev["seq"],
            ev["mode"]) == ("mismatch", "coll", 4, 7, "spin")
    # one terminal fault per life, like kills
    assert inj.coll_op()[0] is None
    inj.fire_coll("mismatch", 4, 7)   # dead: no second park, no event
    assert len(inj.events) == 1
