"""Simulated-fleet survival: correlated daemon loss, bounded reparent
storms, partition heal, uplink-overload shedding, doctor fan-in.

Every test drives the REAL MultiHostLauncher (loss-epoch reparenter,
heartbeat sweep, metrics fan-in) over in-process stub daemons — see
``ompi_tpu.testing.simfleet``.  Worlds are deterministic (fixed seeds,
fixed victim sets computed from the routing tree), so the message-count
assertions are exact, not statistical."""

import time

import pytest

from ompi_tpu.core.config import var_registry
from ompi_tpu.runtime import rml
from ompi_tpu.testing.simfleet import SimFleet


def _expected_reparent(n_daemons: int, victims: list[int]):
    """(orphans, adopters) a batched epoch must produce for ``victims``
    dying on the static routing tree — orphans re-home to their nearest
    live ancestor, deeper descendants keep their links."""
    dead = set(victims)
    orphans = sorted(
        v for v in range(1, n_daemons + 1)
        if v not in dead and (rml.tree_parent(v) or 0) in dead)
    adopters = sorted({rml.nearest_live_ancestor(o, dead)
                       for o in orphans})
    return orphans, adopters


def _fleet(n_daemons, n_ranks, **kw):
    kw.setdefault("seed", 11)
    fleet = SimFleet(n_daemons=n_daemons, n_ranks=n_ranks, **kw)
    fleet.start(timeout=30.0)
    return fleet


# -- boot --------------------------------------------------------------


def test_fleet_boots_and_tears_down_32_ranks():
    fleet = _fleet(4, 32)
    try:
        assert fleet.live_daemons() == 4
        assert fleet.converged()
        assert fleet.self_failed() == {}
        rows, seen = fleet.collect_doctor(timeout=8.0)
        assert seen == {1, 2, 3, 4}
        # rpd=8 == doctor_rows_per_daemon default: no summarization
        assert len(rows) == 32
    finally:
        fleet.stop()


# -- correlated loss: one batched epoch, O(orphans) frames -------------


@pytest.mark.parametrize("n_daemons,n_ranks,victims", [
    (4, 32, [1]),              # mid-tree: 1 owns 3,4
    (16, 128, [4, 5, 6]),      # three interior daemons in one tick
    (64, 512, [16, 17, 18, 19, 20, 21, 22, 23]),   # a whole rack band
])
def test_rack_kill_converges_in_one_bounded_epoch(n_daemons, n_ranks,
                                                  victims):
    orphans, adopters = _expected_reparent(n_daemons, victims)
    assert orphans, "victim set must orphan someone (test bug)"
    fleet = _fleet(n_daemons, n_ranks)
    try:
        fleet.rack_kill(victims)
        dt = fleet.wait_adopted(timeout=15.0)
        assert dt is not None, (
            f"no convergence: self_failed={fleet.self_failed()}")
        la = fleet.launcher
        # ONE batched adoption round for the whole correlated loss
        assert la.reparent_epochs_total == 1
        assert la.reparent_orphans_total == len(orphans)
        # frames = one REPARENT per orphan + one ADOPT per non-HNP
        # adopter group — O(orphans), never O(world) or O(orphans^2)
        expected_frames = len(orphans) + len(
            [a for a in adopters if a != 0])
        assert la.reparent_frames_total == expected_frames
        # nobody died who wasn't killed; nobody gave up waiting
        assert fleet.false_positive_rank_deaths() == []
        assert fleet.self_failed() == {}
        # every orphan took exactly one REPARENT order
        for o in orphans:
            assert fleet.daemons[o].adoptions_total == 1
            assert fleet.daemons[o].node.parent_vpid == \
                rml.nearest_live_ancestor(o, set(victims))
    finally:
        fleet.stop()


def test_three_simultaneous_midtree_kills_are_idempotent():
    """Regression (satellite 1): three interior daemons dying in the
    same tick race three detector families (link EOF at the HNP, orphan
    reports, heartbeat expiry) into the loss queue — the epoch worker
    must coalesce every duplicate into ONE round, adopt each orphan
    exactly once, and leave the effective tree fully live."""
    victims = [4, 5, 6]
    orphans, _adopters = _expected_reparent(16, victims)
    fleet = _fleet(16, 128, hb_period=0.2, hb_timeout=2.0)
    try:
        fleet.rack_kill(victims)
        assert fleet.wait_adopted(timeout=15.0) is not None
        # let the heartbeat sweep cross its timeout too: its late
        # declarations of the same corpses must not start a second round
        time.sleep(2.5)
        la = fleet.launcher
        assert la.reparent_epochs_total == 1
        assert la.reparent_orphans_total == len(orphans)
        assert sum(d.adoptions_total
                   for d in fleet.daemons.values()) == len(orphans)
        dead = set(la._dead_daemons)
        assert dead == set(victims)
        with la._cv:
            eff = dict(la._eff_parent)
        for v, d in fleet.daemons.items():
            if d.alive:
                assert eff.get(v, 0) not in dead
        assert fleet.false_positive_rank_deaths() == []
    finally:
        fleet.stop()


# -- partition: fenced frames drain, no kill storm ---------------------


def test_partition_heals_without_kill_storm():
    """A partitioned subtree drops ALL frames for T seconds with its
    sockets alive.  T < the (world-scaled) heartbeat timeout, so the
    heal must find every daemon alive: zero deaths, zero reparent
    epochs, zero failed ranks — and the fenced metrics stream drains
    (cumulative counters re-land on the next push)."""
    fleet = _fleet(16, 128, hb_period=0.5, hb_timeout=4.0)
    try:
        # vpid 3's subtree: 3, 7, 8, 15, 16
        fenced = [3, 7, 8, 15, 16]
        fleet.partition(fenced)
        # pushes during the fence go nowhere (frames drop, no EOF)
        fleet.metrics_storm(full=False)
        time.sleep(1.0)
        fleet.heal(fenced)
        # beats resume; give the sweep a tick, then push again
        time.sleep(1.0)
        fleet.metrics_storm(full=False)
        time.sleep(0.5)
        la = fleet.launcher
        assert la.reparent_epochs_total == 0
        assert la._dead_daemons == set()
        assert fleet.false_positive_rank_deaths() == []
        assert fleet.self_failed() == {}
        # the drained stream reached the aggregate: every rank row
        # present, including the fenced subtree's
        snap = la.metrics_agg.snapshot()
        ranks = set(snap.get(fleet.job.jobid, {}))
        assert len(ranks) == 128
    finally:
        fleet.stop()


# -- uplink storm: shed-and-count, plane stays serviceable -------------


def test_uplink_storm_sheds_whole_payloads_and_counts_them():
    fleet = _fleet(16, 128, agg_budget_rows=48)
    try:
        fleet.metrics_storm(full=True)
        time.sleep(0.5)
        st = fleet.launcher.metrics_agg.stats()
        assert st["sheds_total"] >= 1
        assert st["shed_rows_total"] > 0
        # shedding is staleness, not corruption: wait a budget window
        # and a small follow-up push must land
        time.sleep(1.1)
        fleet.daemons[1].push_metrics(full=False)
        time.sleep(0.3)
        st2 = fleet.launcher.metrics_agg.stats()
        assert st2["merges_total"] > st["merges_total"]
    finally:
        fleet.stop()


# -- doctor: O(hosts) fan-in with explicit truncation ------------------


def test_doctor_fan_in_is_bounded_per_daemon():
    fleet = _fleet(8, 128, doctor_rows=4)   # 16 ranks/daemon, keep 4
    try:
        rows, seen = fleet.collect_doctor(timeout=8.0)
        assert seen == set(range(1, 9))
        # per daemon: <= limit kept rows + exactly one summary row
        assert len(rows) <= 8 * (4 + 1)
        summaries = [r for r in rows if r.get("summary")]
        assert len(summaries) == 8
        for s in summaries:
            assert s["truncated"] is True
            assert s["ranks_omitted"] == 16 - 4
            assert s["vpid"] in seen
        # every stub rank is accounted for: kept rows + omitted counts
        kept = [r for r in rows if not r.get("summary")]
        assert len(kept) + sum(s["ranks_omitted"]
                               for s in summaries) == 128
    finally:
        fleet.stop()
