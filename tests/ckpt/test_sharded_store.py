"""ShardedSnapshotStore: single-file sharded checkpoints over the
collective MPI-IO stack (ckpt/ routed through io/'s fcoll layer).

≈ the parallel-IO checkpoint layout the reference composes from sstore +
ROMIO: one shared file per array, each rank's block at its displacement,
written by collective write_at_all through the host-aware aggregators.
"""

import os

import numpy as np

from ompi_tpu.ckpt import ShardedSnapshotStore
from tests.mpi.harness import run_ranks


def test_save_load_roundtrip(tmp_path):
    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="j1")
        state = {
            "w": np.arange(8, dtype=np.float32) + 10 * comm.rank,
            "step": np.array([comm.rank], np.int64),
        }
        st.save(3, state)
        back = st.load(3)
        np.testing.assert_array_equal(back["w"], state["w"])
        np.testing.assert_array_equal(back["step"], state["step"])
        return None

    run_ranks(4, body)
    # one shared file per array, not one per rank
    d = str(tmp_path / "j1" / "snapshot_3")
    assert sorted(os.listdir(d)) == ["metadata.json", "step.bin", "w.bin"]
    # rank blocks concatenated in rank order
    w = np.fromfile(os.path.join(d, "w.bin"), np.float32)
    np.testing.assert_array_equal(
        w, np.concatenate([np.arange(8, dtype=np.float32) + 10 * r
                           for r in range(4)]))


def test_ragged_blocks(tmp_path):
    """Per-rank blocks of different sizes/shapes round-trip exactly."""

    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="rag")
        mine = np.full((comm.rank + 1, 3), comm.rank, np.int32)
        st.save(0, {"x": mine})
        back = st.load(0)
        np.testing.assert_array_equal(back["x"], mine)
        # a revived rank can pull another rank's shard
        other = st.load(0, rank=(comm.rank + 1) % comm.size)
        assert other["x"].shape == ((comm.rank + 1) % comm.size + 1, 3)
        return None

    run_ranks(3, body)


def test_commit_record_and_discovery(tmp_path):
    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="disc")
        st.save(1, {"a": np.zeros(2, np.float64)})
        st.save(5, {"a": np.ones(2, np.float64)})
        assert st.snapshots() == [1, 5]
        assert st.latest() == 5
        meta = st.metadata(5)
        assert meta["layout"] == "sharded-file"
        assert meta["arrays"]["a"][comm.rank]["nbytes"] == 16
        return None

    run_ranks(2, body)


def test_snapc_checkpoint_restart_with_sharded_store(tmp_path):
    """ckpt.checkpoint/restart must route through the collective save
    (not the per-rank write_rank protocol) and restore exactly."""
    from ompi_tpu.ckpt import checkpoint, restart

    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="snapc")
        state = {"w": np.arange(6, dtype=np.float32) * (comm.rank + 1)}
        seq = checkpoint(comm, st, state)
        got_seq, got = restart(comm, st)
        assert got_seq == seq
        np.testing.assert_array_equal(got["w"], state["w"])
        return None

    run_ranks(3, body)


def test_write_rank_rejected(tmp_path):
    """The per-rank protocol must fail loudly, not write a layout the
    reader can't restore."""
    from ompi_tpu.mpi.constants import MPIException

    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="rej")
        import pytest

        with pytest.raises(MPIException, match="collective"):
            st.write_rank(0, comm.rank, {"x": np.zeros(1)})
        return None

    run_ranks(1, body)


def test_sharded_save_uses_collective_component(tmp_path, monkeypatch):
    """The store pins fcoll=two_phase: the auto decision would classify
    each rank's contiguous block as individual IO and bypass the
    aggregation layer the store exists to exercise."""
    from ompi_tpu.mpi import io as mio

    seen = []
    orig = mio.File._fcoll_component

    def spy(self, nbytes, runs):
        comp = orig(self, nbytes, runs)
        seen.append(comp)
        return comp

    monkeypatch.setattr(mio.File, "_fcoll_component", spy)

    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="comp")
        st.save(0, {"x": np.zeros(64, np.float32)})
        return None

    run_ranks(2, body)
    assert seen and set(seen) == {"two_phase"}


def test_dtype_mismatch_raises(tmp_path):
    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="dt")
        import pytest

        from ompi_tpu.mpi.constants import MPIException

        bad = np.zeros(4, np.float32 if comm.rank == 0 else np.int64)
        with pytest.raises(MPIException, match="dtype differs"):
            st.save(0, {"x": bad})
        return None

    run_ranks(2, body)


def test_load_rank_compat_and_bf16(tmp_path):
    """load_rank (restart plumbing API) + an extended dtype shard."""
    import ml_dtypes

    def body(comm):
        st = ShardedSnapshotStore(str(tmp_path), comm, job="bf")
        mine = (np.arange(4) + comm.rank).astype(ml_dtypes.bfloat16)
        st.save(0, {"p": mine})
        got = st.load_rank(0, comm.rank)
        np.testing.assert_array_equal(
            got["p"].astype(np.float32), mine.astype(np.float32))
        return None

    run_ranks(2, body)
