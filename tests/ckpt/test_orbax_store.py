"""Orbax-backed snapshot store: pytree round trip, sharded restore,
latest-sequence discovery."""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ompi_tpu.parallel.mesh import make_mesh

pytest.importorskip("orbax.checkpoint")

from ompi_tpu.ckpt.orbax_store import OrbaxStore  # noqa: E402


def test_pytree_roundtrip_and_latest(tmp_path):
    store = OrbaxStore(str(tmp_path), job="t")
    state = {"step": np.int64(7),
             "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4)},
             "mu": np.ones(5, np.float32)}
    store.save(0, state)
    store.save(3, {**state, "step": np.int64(9)})
    assert store.latest() == 3
    back = store.restore(3)
    assert int(back["step"]) == 9
    np.testing.assert_array_equal(back["params"]["w"],
                                  state["params"]["w"])


def test_sharded_restore_onto_mesh(tmp_path):
    mesh = make_mesh({"dp": 4, "sp": 1, "tp": 2})
    sharding = NamedSharding(mesh, P("dp", None))
    x = jax.device_put(np.arange(32, dtype=np.float32).reshape(8, 4),
                       sharding)
    store = OrbaxStore(str(tmp_path), job="s")
    store.save(1, {"x": x})

    abstract = {"x": jax.ShapeDtypeStruct(x.shape, x.dtype,
                                          sharding=sharding)}
    back = store.restore(1, abstract)["x"]
    assert back.sharding == sharding
    assert back.sharding.shard_shape(back.shape)[0] == 2  # 8 rows / dp 4
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
