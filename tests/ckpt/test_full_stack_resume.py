"""Everything-composed resume: every training feature ON at once.

The per-feature trajectory tests (zero1, grad-accum, param-dtype, data
pipeline) each pass alone; this test turns them ALL on over one
dp×sp×tp mesh — ZeRO-1 sharded optimizer + bf16 param storage with f32
master + bf16 Adam moments + 2-microbatch gradient accumulation + the
prefetching data pipeline — snapshots mid-run, restores into fresh
arrays, resumes the data stream by step counter, and requires the
resumed trajectory to EQUAL the uninterrupted one.  Cross-feature
interactions (master-weight trees inside the zero1 state, bf16 leaves
through the npz store, stream step accounting under accumulation) have
nowhere to hide.
"""

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.models import data as data_mod
from ompi_tpu.models import transformer as tfm
from ompi_tpu.parallel.mesh import make_mesh

CFG = tfm.TransformerConfig(
    vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128, seq=32,
    attention="xla", compute_dtype="float32",
    zero1_axis="dp", param_dtype="bfloat16", adam_mu_dtype="bfloat16",
    grad_accum=2)

BATCH = 4          # 2 microbatches of 2 under grad_accum
SNAP_AT = 3        # steps before the snapshot
MORE = 2           # steps after


def _flat(tree):
    return {f"k{i}": np.asarray(leaf) for i, leaf in
            enumerate(jax.tree_util.tree_leaves(tree))}


def _unflat(tree_like, blobs):
    leaves = jax.tree_util.tree_leaves(tree_like)
    treedef = jax.tree_util.tree_structure(tree_like)
    out = [jax.device_put(blobs[f"k{i}"], like.sharding)
           for i, like in enumerate(leaves)]
    return jax.tree_util.tree_unflatten(treedef, out)


def _stream(source, mesh, start_step):
    return data_mod.train_stream(source, mesh, batch=BATCH, seq=CFG.seq,
                                 start_step=start_step)


def test_all_features_resume_exactly(tmp_path):
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    source = data_mod.ArraySource(
        (np.arange(4096) % CFG.vocab).astype(np.int32), seed=3)

    params = tfm.init_params(CFG)
    assert str(jax.tree_util.tree_leaves(params)[0].dtype) == "bfloat16"
    step, init_opt = tfm.make_train_step(CFG, mesh, lr=1e-2)
    opt_state = init_opt(params)

    stream = _stream(source, mesh, 0)
    for _ in range(SNAP_AT):
        params, opt_state, _ = step(params, opt_state, next(stream))

    store = SnapshotStore(str(tmp_path), job="fullstack")
    store.write_rank(0, 0, {**{f"p_{k}": v for k, v in params.items()},
                            **_flat(opt_state)})
    store.commit(0, nranks=1, extra={"step": SNAP_AT})

    # uninterrupted reference trajectory
    ref_p, ref_s = params, opt_state
    ref_losses = []
    for _ in range(MORE):
        ref_p, ref_s, loss = step(ref_p, ref_s, next(stream))
        ref_losses.append(float(loss))
    stream.close()

    # restore into FRESH arrays + resume the stream at the saved step
    meta = store.metadata(0)
    assert meta["step"] == SNAP_AT
    blobs = store.load_rank(0, 0)
    specs = tfm.param_specs(P, CFG, mesh)
    params2 = {k: jax.device_put(blobs[f"p_{k}"],
                                 NamedSharding(mesh, specs[k]))
               for k in params}
    assert str(jax.tree_util.tree_leaves(params2)[0].dtype) == "bfloat16"
    opt_state2 = _unflat(opt_state, blobs)
    stream2 = _stream(source, mesh, meta["step"])
    got_losses = []
    for _ in range(MORE):
        params2, opt_state2, loss2 = step(params2, opt_state2,
                                          next(stream2))
        got_losses.append(float(loss2))
    stream2.close()

    # exact trajectory: same losses, same final params bit for bit
    assert got_losses == ref_losses
    for k in ref_p:
        np.testing.assert_array_equal(np.asarray(ref_p[k]),
                                      np.asarray(params2[k]), err_msg=k)


def test_moe_composed_resume_exactly(tmp_path):
    """Same composition with the MoE family: switch-MoE experts over a
    dp×ep mesh + ZeRO-1 + bf16 storage/f32 master + grad accumulation,
    snapshot/restore mid-run, exact trajectory."""
    cfg = tfm.TransformerConfig(
        vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128, seq=32,
        attention="xla", compute_dtype="float32", moe_experts=8,
        remat=False, zero1_axis="dp", param_dtype="bfloat16",
        adam_mu_dtype="bfloat16", grad_accum=2)
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 1, "ep": 4})
    rng = np.random.default_rng(7)
    toks = [rng.integers(0, cfg.vocab, size=(BATCH, cfg.seq))
            .astype(np.int32) for _ in range(SNAP_AT + MORE)]

    params = tfm.init_params(cfg)
    step, init_opt = tfm.make_train_step(cfg, mesh, lr=1e-2)
    opt_state = init_opt(params)
    for i in range(SNAP_AT):
        params, opt_state, _ = step(params, opt_state, toks[i])

    store = SnapshotStore(str(tmp_path), job="moe")
    store.write_rank(0, 0, {**{f"p_{k}": v for k, v in params.items()},
                            **_flat(opt_state)})
    store.commit(0, nranks=1)

    ref_p, ref_s, ref_losses = params, opt_state, []
    for i in range(MORE):
        ref_p, ref_s, loss = step(ref_p, ref_s, toks[SNAP_AT + i])
        ref_losses.append(float(loss))

    blobs = store.load_rank(0, 0)
    specs = tfm.param_specs(P, cfg, mesh)
    params2 = {k: jax.device_put(blobs[f"p_{k}"],
                                 NamedSharding(mesh, specs[k]))
               for k in params}
    opt_state2 = _unflat(opt_state, blobs)
    got_losses = []
    for i in range(MORE):
        params2, opt_state2, loss2 = step(params2, opt_state2,
                                          toks[SNAP_AT + i])
        got_losses.append(float(loss2))
    assert got_losses == ref_losses


def test_train_snapshot_restore_decode(tmp_path):
    """The serving handoff: train, snapshot, restore into fresh arrays,
    greedy-decode — the decoder's output from restored params must equal
    its output from the live ones (bf16 storage included)."""
    from ompi_tpu.models.decode import make_decoder

    cfg = tfm.TransformerConfig(
        vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128, seq=48,
        attention="xla", compute_dtype="float32",
        param_dtype="bfloat16", adam_mu_dtype="bfloat16")
    mesh = make_mesh({"dp": 2, "sp": 1, "tp": 2},
                     devices=jax.devices()[:4])
    rng = np.random.default_rng(9)
    toks = rng.integers(0, cfg.vocab, size=(4, 32)).astype(np.int32)

    params = tfm.init_params(cfg)
    step, init_opt = tfm.make_train_step(cfg, mesh, lr=1e-2)
    opt_state = init_opt(params)
    for _ in range(2):
        params, opt_state, _ = step(params, opt_state, toks)

    dec = make_decoder(cfg, mesh, max_new=8)
    prompt = toks[:, :16]
    want = np.asarray(dec(params, prompt))

    store = SnapshotStore(str(tmp_path), job="serve")
    store.write_rank(0, 0, {k: v for k, v in params.items()})
    store.commit(0, nranks=1)
    blobs = store.load_rank(0, 0)
    specs = tfm.param_specs(P, cfg, mesh)
    params2 = {k: jax.device_put(blobs[k], NamedSharding(mesh, specs[k]))
               for k in params}
    got = np.asarray(dec(params2, prompt))
    np.testing.assert_array_equal(got, want)
    assert got.shape == (4, 16 + 8)
