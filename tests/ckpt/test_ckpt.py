"""Checkpoint/restart tests — store layouts, two-phase commit, coordinated
collective snapshots, async manager, message logging.

≈ exercising the reference's crs/snapc/sstore/vprotocol stack through state
injection, the way its errmgr/dfs test hooks do.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from ompi_tpu import ckpt
from ompi_tpu.mpi.constants import MPIException
from tests.mpi.harness import run_ranks


# ---------------------------------------------------------------------------
# store (single process)
# ---------------------------------------------------------------------------

def test_store_roundtrip_and_commit_gate(tmp_path):
    st = ckpt.SnapshotStore(str(tmp_path))
    st.write_rank(0, 0, {"w": np.arange(4.0), "step": np.int64(7)})
    # uncommitted → invisible + unloadable
    assert st.snapshots() == []
    with pytest.raises(MPIException):
        st.load_rank(0, 0)
    st.commit(0, nranks=1)
    assert st.snapshots() == [0]
    out = st.load_rank(0, 0)
    np.testing.assert_array_equal(out["w"], np.arange(4.0))
    assert int(out["step"]) == 7


def test_store_roundtrips_bfloat16_dtype(tmp_path):
    """npz drops ml_dtypes names (bf16 loads back as raw |V2 without the
    key-tag scheme) — bf16 training state (param_dtype/adam_mu_dtype)
    must come back with its dtype intact, for both npz stores."""
    import ml_dtypes

    vals = np.array([1.5, -2.25, 0.125], dtype=ml_dtypes.bfloat16)
    for store in (ckpt.SnapshotStore(str(tmp_path / "c")),
                  ckpt.StagedStore(str(tmp_path / "s"),
                                   str(tmp_path / "local"))):
        store.write_rank(0, 0, {"w": vals, "f32": np.arange(2.0)})
        store.commit(0, nranks=1)
        out = store.load_rank(0, 0)
        assert out["w"].dtype == vals.dtype, out["w"].dtype
        np.testing.assert_array_equal(out["w"], vals)
        assert out["f32"].dtype == np.arange(2.0).dtype  # natives untouched


def test_store_exotic_dtype_edge_cases(tmp_path):
    """The sidecar dtype manifest must not break what round-tripped
    before, and must be immune to hostile user keys: plain void dtypes
    stay raw, structured records pass through, keys that look like the
    old tag suffixes (or collide with same-itemsize dtypes) are never
    reinterpreted or dropped, and the one reserved manifest key
    raises."""
    import ml_dtypes

    st = ckpt.SnapshotStore(str(tmp_path))
    rec = np.zeros(2, dtype=[("a", "f4"), ("b", "i4")])
    st.write_rank(0, 0, {
        "bf": np.array([1.5, -2.0], ml_dtypes.bfloat16),
        "raw": np.zeros(3, dtype="V4"),            # unregistered void
        "rec__dtype_tbl": rec,                     # structured + suffix
        "x": np.arange(3.0),                       # sibling of the next
        "x__dtype_float32": np.zeros(3, "V4"),     # hostile stem/suffix
        "g__dtype_float64": np.zeros(3, "V4"),     # itemsize mismatch
    })
    st.commit(0, nranks=1)
    out = st.load_rank(0, 0)
    assert out["bf"].dtype.name == "bfloat16"
    assert out["raw"].dtype.itemsize == 4 and out["raw"].dtype.kind == "V"
    assert out["rec__dtype_tbl"].dtype.names == ("a", "b")
    assert out["x"].dtype == np.float64            # sibling survives
    assert out["x__dtype_float32"].dtype.kind == "V"   # NOT viewed
    assert out["g__dtype_float64"].dtype.kind == "V"
    assert len(out) == 6

    from ompi_tpu.ckpt.store import _DTYPE_MANIFEST

    with pytest.raises(MPIException, match="reserved"):
        st.write_rank(1, 0, {_DTYPE_MANIFEST: np.zeros(1)})


def test_store_commit_requires_all_ranks(tmp_path):
    st = ckpt.SnapshotStore(str(tmp_path))
    st.write_rank(0, 0, {"x": np.zeros(1)})
    with pytest.raises(MPIException):
        st.commit(0, nranks=2)          # rank 1 never wrote


def test_store_gc_keeps_newest(tmp_path):
    st = ckpt.SnapshotStore(str(tmp_path))
    for seq in range(4):
        st.write_rank(seq, 0, {"x": np.full(2, seq)})
        st.commit(seq, 1)
    removed = st.gc(keep_last=2)
    assert removed == [0, 1]
    assert st.snapshots() == [2, 3]
    assert st.latest() == 3


def test_staged_store_stages_into_central(tmp_path):
    st = ckpt.StagedStore(str(tmp_path / "central"),
                          str(tmp_path / "local"))
    st.write_rank(0, 0, {"x": np.ones(3)})
    st.commit(0, 1)
    # the staged local file is gone, the central one is live
    assert os.listdir(str(tmp_path / "local")) == []
    np.testing.assert_array_equal(st.load_rank(0, 0)["x"], np.ones(3))


# ---------------------------------------------------------------------------
# coordinated checkpoint/restart (multi-rank)
# ---------------------------------------------------------------------------

def test_checkpoint_restart_roundtrip(tmp_path):
    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        state = {"w": np.arange(8.0) + comm.rank * 10,
                 "step": np.int64(3)}
        seq = ckpt.checkpoint(comm, st, state)
        got_seq, got = ckpt.restart(comm, st)
        return seq, got_seq, got

    for r, (seq, got_seq, got) in enumerate(run_ranks(3, body)):
        assert seq == got_seq == 0
        np.testing.assert_array_equal(got["w"], np.arange(8.0) + r * 10)
        assert int(got["step"]) == 3


def test_checkpoint_seq_advances_and_keep_last(tmp_path):
    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        for i in range(3):
            ckpt.checkpoint(comm, st, {"x": np.full(2, i)},
                            keep_last=2)
        comm.barrier()
        return st.snapshots()

    for snaps in run_ranks(2, body):
        assert snaps == [1, 2]


def test_checkpoint_failure_is_collective(tmp_path):
    """If one rank can't write, NO rank commits (all-or-nothing)."""
    base = str(tmp_path)

    class BrokenStore(ckpt.SnapshotStore):
        def write_rank(self, seq, rank, state):
            if rank == 1:
                raise OSError("disk full")
            return super().write_rank(seq, rank, state)

    def body(comm):
        st = BrokenStore(base)
        try:
            ckpt.checkpoint(comm, st, {"x": np.zeros(1)})
        except MPIException:
            return st.latest()
        return "no-raise"

    assert run_ranks(2, body) == [None, None]


def test_commit_failure_raises_on_all_ranks(tmp_path):
    """rank 0's commit throwing must not strand peers in a barrier —
    everyone gets the MPIException (regression)."""
    base = str(tmp_path)

    class CommitBroken(ckpt.SnapshotStore):
        def commit(self, seq, nranks, extra=None):
            raise OSError("metadata write failed")

    def body(comm):
        st = CommitBroken(base)
        try:
            ckpt.checkpoint(comm, st, {"x": np.zeros(1)})
        except MPIException as e:
            return "commit failed" in str(e)
        return False

    assert all(run_ranks(3, body, timeout=20.0))


def test_restart_with_restore_fn(tmp_path):
    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        ckpt.checkpoint(comm, st, {"w": np.arange(4, dtype=np.float32)})
        _, got = ckpt.restart(
            comm, st,
            restore_fn=lambda name, arr: arr.astype(np.float64) * 2)
        return got["w"]

    for w in run_ranks(2, body):
        assert w.dtype == np.float64
        np.testing.assert_array_equal(w, np.arange(4.0) * 2)


def test_restart_no_snapshot_raises(tmp_path):
    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        try:
            ckpt.restart(comm, st)
        except MPIException:
            return True
        return False

    assert all(run_ranks(2, body))


# ---------------------------------------------------------------------------
# manager (interval policy + async)
# ---------------------------------------------------------------------------

def test_manager_interval_policy(tmp_path):
    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        mgr = ckpt.CheckpointManager(comm, st, interval=2, keep_last=10)
        taken = []
        for step in range(5):
            seq = mgr.maybe_checkpoint(step, {"s": np.int64(step)})
            if seq is not None:
                taken.append(seq)
        mgr.wait()
        return taken, st.snapshots()

    for taken, snaps in run_ranks(2, body):
        assert taken == [0, 2, 4]
        assert snaps == [0, 2, 4]


def test_manager_async_save_and_restore(tmp_path):
    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        mgr = ckpt.CheckpointManager(comm, st, interval=1, keep_last=5,
                                     async_save=True)
        state = {"w": np.arange(6.0) + comm.rank}
        mgr.save(0, state)
        state["w"] += 100          # mutate right after: snapshot is a copy
        # application traffic while the save is in flight must not
        # cross-match the checkpoint collectives (private dup'd comm)
        comm.allreduce(np.ones(4))
        mgr.wait()
        _, got = mgr.restore()
        return got["w"]

    for r, w in enumerate(run_ranks(2, body)):
        np.testing.assert_array_equal(w, np.arange(6.0) + r)


def test_manager_auto_restore_rank_override(tmp_path, monkeypatch):
    """The manager wrapper forwards ``auto_restore``'s per-rank-store
    rank override: apps keying one store PER rank write their shard
    under rank key 0 (the selfheal/chaos recipe), so the wrapper must
    not hard-code ``comm.rank`` for the lookup."""
    from ompi_tpu.ckpt import snapc

    base = str(tmp_path)
    monkeypatch.setattr(snapc, "restart_incarnation", lambda: 1)

    def body(comm):
        st = ckpt.SnapshotStore(os.path.join(base, f"rank{comm.rank}"))
        mgr = ckpt.CheckpointManager(comm, st, interval=1)
        st.write_rank(5, 0, {"acc": np.float64(comm.rank + 41.0)})
        st.commit(5, nranks=1)
        seq, state = mgr.auto_restore(rank=0)
        return seq, float(state["acc"])

    for r, (seq, acc) in enumerate(run_ranks(2, body)):
        assert seq == 5
        assert acc == r + 41.0


def test_checkpoint_jax_device_arrays(tmp_path):
    """Device arrays are pulled to host on save and re-placed on restore."""
    import jax
    import jax.numpy as jnp

    base = str(tmp_path)

    def body(comm):
        st = ckpt.SnapshotStore(base)
        w = jnp.arange(8.0) * (comm.rank + 1)
        ckpt.checkpoint(comm, st, {"w": w})
        _, got = ckpt.restart(
            comm, st, restore_fn=lambda name, arr: jax.device_put(arr))
        assert hasattr(got["w"], "devices")
        return np.asarray(got["w"])

    for r, w in enumerate(run_ranks(2, body)):
        np.testing.assert_array_equal(w, np.arange(8.0) * (r + 1))


# ---------------------------------------------------------------------------
# message logging (vprotocol building block)
# ---------------------------------------------------------------------------

def test_msglog_records_and_marks():
    def body(comm):
        with ckpt.MessageLog(comm) as log:
            peer = (comm.rank + 1) % comm.size
            rr = comm.irecv(source=(comm.rank - 1) % comm.size, tag=5)
            comm.send(np.full(3, comm.rank), dest=peer, tag=5)
            rr.wait()
            n_before = len(log.pending())
            log.mark()
            n_after = len(log.pending())
            comm.barrier()             # internal tags: never logged
            return n_before, n_after, len(log.pending())

    for before, after, coll in run_ranks(2, body):
        assert before == 1 and after == 0 and coll == 0


def test_msglog_replay_redelivers():
    def body(comm):
        log = ckpt.MessageLog(comm).attach()
        try:
            if comm.rank == 0:
                comm.send(np.array([1.0, 2.0]), dest=1, tag=9)
                comm.send(np.array([3.0]), dest=1, tag=9)
                comm.barrier()
                # "rank 1 restarted and lost them" → replay
                n = log.replay(to_rank=1)
                comm.barrier()
                return n
            first = comm.recv(source=0, tag=9)
            second = comm.recv(source=0, tag=9)
            comm.barrier()
            re1 = comm.recv(source=0, tag=9)
            re2 = comm.recv(source=0, tag=9)
            comm.barrier()
            np.testing.assert_array_equal(first, re1)
            np.testing.assert_array_equal(second, re2)
            return (first, second)
        finally:
            log.detach()

    res = run_ranks(2, body)
    assert res[0] == 2


def test_msglog_byte_cap_evicts_oldest_and_blocks_replay():
    def body(comm):
        if comm.rank == 0:
            log = ckpt.MessageLog(comm, max_bytes=100).attach()
            try:
                for i in range(5):
                    comm.send(np.full(5, i), dest=1, tag=2)  # 40 B each
                pend = log.pending()
                try:
                    log.replay(to_rank=1)   # incomplete → must refuse
                except MPIException:
                    refused = True
                else:
                    refused = False
                vals = [int(p[2][0]) for p in pend]
                nbytes = log.nbytes
                log.mark()
                return (vals, nbytes, True, refused, log.complete)
            finally:
                log.detach()
        for _ in range(5):
            comm.recv(source=0, tag=2)
        return None

    vals, nbytes, _, refused, marked = run_ranks(2, body)[0]
    assert vals == [3, 4] and nbytes == 80
    assert refused            # partial replay is an error, not silence
    assert marked             # mark() resets completeness


def test_msglog_failed_send_not_logged():
    def body(comm):
        if comm.rank != 0:
            return None
        log = ckpt.MessageLog(comm).attach()
        try:
            try:
                comm.isend(np.zeros(1), dest=99, tag=1)   # bad dest
            except MPIException:
                pass
            return len(log.pending())
        finally:
            log.detach()

    assert run_ranks(2, body)[0] == 0


def test_event_log_records_wildcard_order():
    from ompi_tpu.ckpt.msglog import EventLog
    from ompi_tpu.mpi.constants import ANY_SOURCE, ANY_TAG

    def body(comm):
        if comm.rank == 0:
            with EventLog(comm) as ev:
                a = comm.recv(source=ANY_SOURCE, tag=ANY_TAG)   # ANY_SOURCE/ANY_TAG
                b = comm.recv(source=ANY_SOURCE, tag=ANY_TAG)
                order = ev.events()
            assert len(order) == 2
            assert {o[0] for o in order} == {1, 2}
            # recorded order matches payload arrival order
            assert int(a[0]) == order[0][0] and int(b[0]) == order[1][0]
            return order
        else:
            import time
            time.sleep(0.02 * comm.rank)           # stagger arrivals
            comm.send(np.array([comm.rank]), dest=0, tag=comm.rank)
        return None

    from tests.mpi.harness import run_ranks
    order = run_ranks(3, body)[0]
    assert order is not None


def test_event_log_replay_forces_recorded_order():
    from ompi_tpu.ckpt.msglog import EventLog
    from ompi_tpu.mpi.constants import ANY_SOURCE, ANY_TAG

    recorded = [(2, 7), (1, 7)]                    # 2 first, then 1

    def body(comm):
        if comm.rank == 0:
            with EventLog(comm, replay=recorded) as ev:
                assert ev.replaying
                a = comm.recv(source=ANY_SOURCE, tag=ANY_TAG)   # rewritten → (2, 7)
                b = comm.recv(source=ANY_SOURCE, tag=ANY_TAG)   # rewritten → (1, 7)
                assert not ev.replaying
            # forced order 2-then-1 even though rank 1 sent FIRST
            return int(a[0]), int(b[0])
        else:
            import time
            if comm.rank == 2:
                time.sleep(0.05)                   # 1 races ahead of 2
            comm.send(np.array([comm.rank]), dest=0, tag=7)
        return None

    from tests.mpi.harness import run_ranks
    assert run_ranks(3, body)[0] == (2, 1)


def test_event_log_incomplete_history_raises():
    from ompi_tpu.ckpt.msglog import EventLog
    from ompi_tpu.mpi.constants import ANY_SOURCE, ANY_TAG, MPIException

    def body(comm):
        if comm.rank == 0:
            ev = EventLog(comm).attach()
            req = comm.irecv(source=ANY_SOURCE, tag=ANY_TAG)    # never completes yet
            try:
                with pytest.raises(MPIException):
                    ev.events()
            finally:
                comm.send(np.array([0.0]), dest=0, tag=3)  # self-satisfy
                req.wait()
                ev.detach()
        comm.barrier()
        return True

    from tests.mpi.harness import run_ranks
    assert all(run_ranks(2, body))
