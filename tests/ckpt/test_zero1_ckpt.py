"""Checkpoint/restore of ZeRO-1-sharded training state.

The optimizer tree lives 1/dp per device; a snapshot gathers it to
host, and restore re-commits the leaves to their dp sharding — training
after restore must continue the original trajectory exactly.
"""

import dataclasses

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.models import transformer as tfm
from ompi_tpu.parallel.mesh import make_mesh

CFG = tfm.TransformerConfig(
    vocab=128, d_model=64, n_heads=4, n_layers=2, d_ff=128, seq=32,
    attention="xla", compute_dtype="float32", zero1_axis="dp")


def _flat(tree):
    return {f"k{i}": np.asarray(leaf) for i, leaf in
            enumerate(jax.tree_util.tree_leaves(tree))}


def _unflat(tree_like, blobs, mesh):
    leaves = jax.tree_util.tree_leaves(tree_like)
    treedef = jax.tree_util.tree_structure(tree_like)
    out = []
    for i, like in enumerate(leaves):
        arr = blobs[f"k{i}"]
        out.append(jax.device_put(arr, like.sharding))
    return jax.tree_util.tree_unflatten(treedef, out)


def test_zero1_state_snapshot_restore_continues_exactly(tmp_path):
    mesh = make_mesh({"dp": 2, "sp": 2, "tp": 2})
    rng = np.random.default_rng(5)
    toks = rng.integers(0, CFG.vocab, size=(4, CFG.seq)).astype(np.int32)

    params = tfm.init_params(CFG)
    step, init_opt = tfm.make_train_step(CFG, mesh, lr=1e-2)
    opt_state = init_opt(params)

    # 2 steps, snapshot, 2 more steps = the reference trajectory
    for _ in range(2):
        params, opt_state, _ = step(params, opt_state, toks)
    store = SnapshotStore(str(tmp_path), job="z1")
    store.write_rank(0, 0, {**{f"p_{k}": v for k, v in params.items()},
                            **_flat(opt_state)})
    store.commit(0, nranks=1)
    ref_p, ref_s = params, opt_state
    for _ in range(2):
        ref_p, ref_s, ref_loss = step(ref_p, ref_s, toks)

    # restore into FRESH arrays (the respawn path): params replicated,
    # optimizer leaves re-committed to their (dp, n) sharding
    blobs = store.load_rank(0, 0)
    specs = tfm.param_specs(P, CFG, mesh)
    params2 = {k: jax.device_put(blobs[f"p_{k}"],
                                 NamedSharding(mesh, specs[k]))
               for k in params}
    # sanity: saved master leaves are the gathered (dp, n) arrays
    assert blobs["k0"].ndim >= 1
    opt_state2 = _unflat(opt_state, blobs, mesh)
    m_leaf = jax.tree_util.tree_leaves(opt_state2)[0]
    if hasattr(m_leaf, "sharding") and m_leaf.ndim == 2:
        assert m_leaf.sharding.shard_shape(m_leaf.shape)[0] \
            == m_leaf.shape[0] // 2

    got_p, got_s = params2, opt_state2
    for _ in range(2):
        got_p, got_s, got_loss = step(got_p, got_s, toks)
    assert float(got_loss) == float(ref_loss)
    np.testing.assert_array_equal(np.asarray(got_p["w1"]),
                                  np.asarray(ref_p["w1"]))
