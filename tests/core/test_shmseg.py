"""Shared-segment framework (≈ opal/mca/shmem mmap component)."""

import os

import pytest

from ompi_tpu.core import shmseg


def test_create_attach_roundtrip():
    with shmseg.create("test_seg_rt", 4096) as seg:
        assert seg.size == 4096
        seg.buf[:5] = b"hello"
        att = shmseg.attach(seg.path)
        try:
            assert att.size == 4096
            assert bytes(att.buf[:5]) == b"hello"
            att.buf[5:7] = b"!!"          # both directions
            assert bytes(seg.buf[:7]) == b"hello!!"
        finally:
            att.detach()
    assert not os.path.exists(seg.path)    # creator unlinked


def test_attach_survives_unlink():
    seg = shmseg.create("test_seg_unlink", 128)
    att = shmseg.attach(seg.path)
    seg.buf[:3] = b"abc"
    seg.close()                            # unlink + detach
    # the attached mapping stays valid after the name is gone
    assert bytes(att.buf[:3]) == b"abc"
    att.detach()


def test_attach_rejects_garbage(tmp_path):
    p = tmp_path / "junk"
    p.write_bytes(b"\x00" * 64)
    with pytest.raises(OSError):
        shmseg.attach(str(p))


def test_attach_missing_raises():
    with pytest.raises(OSError):
        shmseg.attach(os.path.join(shmseg.backing_dir(), "no-such-seg"))
