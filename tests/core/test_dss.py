"""Tests for DSS serialization (≈ test/dss/)."""

import numpy as np
import pytest

from ompi_tpu.core.dss import Buffer, DSSError, pack, unpack


def roundtrip(*values):
    return unpack(pack(*values))


def test_scalars():
    assert roundtrip(42, -7, 3.5, True, False, None) == [42, -7, 3.5, True, False, None]


def test_strings_and_bytes():
    vals = ["hello", "", "üñïçødé", b"\x00\xff raw"]
    assert roundtrip(*vals) == vals


def test_containers():
    v = {"a": [1, 2, {"n": None}], "t": (1, "x"), "b": b"z"}
    (out,) = roundtrip(v)
    assert out == v
    assert isinstance(out["t"], tuple)


def test_ndarray_roundtrip():
    for dt in (np.float32, np.int64, np.uint8, np.complex64):
        arr = (np.arange(24).reshape(2, 3, 4) % 7).astype(dt)
        (out,) = roundtrip(arr)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == arr.dtype


def test_ndarray_zero_dim():
    arr = np.float64(3.25)
    (out,) = roundtrip(np.asarray(arr))
    assert out.shape == () and out == 3.25


def test_noncontiguous_array_packed_contiguously():
    arr = np.arange(100).reshape(10, 10)[::2, ::3]
    (out,) = roundtrip(arr)
    np.testing.assert_array_equal(out, arr)


def test_type_checked_unpack():
    buf = Buffer(pack(5))
    with pytest.raises(DSSError):
        buf.unpack(expect=str)


def test_underrun():
    buf = Buffer(pack(12345)[:-2])
    with pytest.raises(DSSError):
        buf.unpack()


def test_unpackable_type_rejected():
    with pytest.raises(DSSError):
        pack(object())


def test_streaming_partial_unpack():
    data = pack(1, "two", 3.0)
    assert unpack(data, n=2) == [1, "two"]


def test_fast_unpack_truncation_raises():
    """The fast codecs must fail as loudly as the Buffer path on torn
    frames (a short tcp read / truncated shm frame must never yield a
    silently-truncated value)."""
    import numpy as np
    import pytest

    from ompi_tpu.core import dss

    for v in ("hello world, a long string", b"\x01" * 64,
              {"k": "a long enough value"}, [1, 2, "tail string"],
              np.arange(32)):
        blob = dss.pack(v)
        for cut in (len(blob) // 2, len(blob) - 1, 3):
            with pytest.raises(dss.DSSError):
                dss.unpack(blob[:cut])


def test_fast_codec_wire_identical_to_buffer():
    import numpy as np

    from ompi_tpu.core import dss

    vals = [None, True, 7, -1, 2.5, "s", b"b", [1, [2]], (3,),
            {"a": 1, "b": [None, "x"]}]
    fast = dss.pack(*vals)
    buf = dss.Buffer()
    for v in vals:
        buf.pack(v)
    assert fast == buf.bytes()
    assert dss.unpack(fast) == vals


def test_fastdss_parity_fuzz():
    """The compiled codec and the python codec must agree byte-for-byte
    on random nested structures, and decode each other's output."""
    import random

    import pytest

    from ompi_tpu import _native
    from ompi_tpu.core import dss

    fast = _native.fastdss()
    if fast is None:
        pytest.skip("fastdss did not build")
    rng = random.Random(7)

    def gen(depth=0):
        kinds = ["int", "str", "bytes", "float", "bool", "none"]
        if depth < 3:
            kinds += ["list", "tuple", "dict"] * 2
        k = rng.choice(kinds)
        if k == "int":
            return rng.randint(-2**62, 2**62)
        if k == "str":
            return "".join(chr(rng.randint(32, 0x2FA0))
                           for _ in range(rng.randint(0, 12)))
        if k == "bytes":
            return bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 20)))
        if k == "float":
            return rng.uniform(-1e12, 1e12)
        if k == "bool":
            return rng.random() < 0.5
        if k == "none":
            return None
        if k in ("list", "tuple"):
            items = [gen(depth + 1) for _ in range(rng.randint(0, 5))]
            return items if k == "list" else tuple(items)
        return {f"k{i}": gen(depth + 1) for i in range(rng.randint(0, 5))}

    for _ in range(300):
        v = gen()
        ref = dss.Buffer()
        ref.pack(v)
        wire_ref = ref.bytes()
        wire_fast = fast.pack((v,))
        assert wire_fast == wire_ref, v
        assert fast.unpack(wire_ref, 1) == [v]
        assert dss.unpack(wire_fast, n=1) == [v]


def test_fastdss_hostile_lengths():
    """Hostile declared lengths must raise, never over-allocate or
    silently truncate."""
    import struct as _s

    import pytest

    from ompi_tpu import _native
    from ompi_tpu.core import dss

    fast = _native.fastdss()
    if fast is None:
        pytest.skip("fastdss did not build")
    # string claiming 4GB, list claiming 1e9 items, dict likewise
    for blob in (bytes([3]) + _s.pack("<I", 0xFFFFFFF0) + b"xy",
                 bytes([7]) + _s.pack("<I", 10**9),
                 bytes([8]) + _s.pack("<I", 10**9)):
        with pytest.raises(dss.DSSError):
            dss.unpack(blob)
