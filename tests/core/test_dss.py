"""Tests for DSS serialization (≈ test/dss/)."""

import numpy as np
import pytest

from ompi_tpu.core.dss import Buffer, DSSError, pack, unpack


def roundtrip(*values):
    return unpack(pack(*values))


def test_scalars():
    assert roundtrip(42, -7, 3.5, True, False, None) == [42, -7, 3.5, True, False, None]


def test_strings_and_bytes():
    vals = ["hello", "", "üñïçødé", b"\x00\xff raw"]
    assert roundtrip(*vals) == vals


def test_containers():
    v = {"a": [1, 2, {"n": None}], "t": (1, "x"), "b": b"z"}
    (out,) = roundtrip(v)
    assert out == v
    assert isinstance(out["t"], tuple)


def test_ndarray_roundtrip():
    for dt in (np.float32, np.int64, np.uint8, np.complex64):
        arr = (np.arange(24).reshape(2, 3, 4) % 7).astype(dt)
        (out,) = roundtrip(arr)
        np.testing.assert_array_equal(out, arr)
        assert out.dtype == arr.dtype


def test_ndarray_zero_dim():
    arr = np.float64(3.25)
    (out,) = roundtrip(np.asarray(arr))
    assert out.shape == () and out == 3.25


def test_noncontiguous_array_packed_contiguously():
    arr = np.arange(100).reshape(10, 10)[::2, ::3]
    (out,) = roundtrip(arr)
    np.testing.assert_array_equal(out, arr)


def test_type_checked_unpack():
    buf = Buffer(pack(5))
    with pytest.raises(DSSError):
        buf.unpack(expect=str)


def test_underrun():
    buf = Buffer(pack(12345)[:-2])
    with pytest.raises(DSSError):
        buf.unpack()


def test_unpackable_type_rejected():
    with pytest.raises(DSSError):
        pack(object())


def test_streaming_partial_unpack():
    data = pack(1, "two", 3.0)
    assert unpack(data, n=2) == [1, "two"]


def test_fast_unpack_truncation_raises():
    """The fast codecs must fail as loudly as the Buffer path on torn
    frames (a short tcp read / truncated shm frame must never yield a
    silently-truncated value)."""
    import numpy as np
    import pytest

    from ompi_tpu.core import dss

    for v in ("hello world, a long string", b"\x01" * 64,
              {"k": "a long enough value"}, [1, 2, "tail string"],
              np.arange(32)):
        blob = dss.pack(v)
        for cut in (len(blob) // 2, len(blob) - 1, 3):
            with pytest.raises(dss.DSSError):
                dss.unpack(blob[:cut])


def test_fast_codec_wire_identical_to_buffer():
    import numpy as np

    from ompi_tpu.core import dss

    vals = [None, True, 7, -1, 2.5, "s", b"b", [1, [2]], (3,),
            {"a": 1, "b": [None, "x"]}]
    fast = dss.pack(*vals)
    buf = dss.Buffer()
    for v in vals:
        buf.pack(v)
    assert fast == buf.bytes()
    assert dss.unpack(fast) == vals
