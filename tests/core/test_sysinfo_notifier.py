"""sysinfo (timer/pstat/backtrace), notifier, mpiext, schizo — the small
always-built frameworks (≈ opal/mca/{timer,pstat,backtrace},
orte/mca/notifier, ompi/mpiext, orte/mca/schizo)."""

import os
import subprocess
import sys

import pytest

from ompi_tpu.core.sysinfo import Timer, install_backtrace_handlers, proc_stats


def test_timer_monotone_interval():
    t = Timer()
    a = Timer.cycles()
    b = Timer.cycles()
    assert b >= a
    dt = t.restart()
    assert dt >= 0
    assert t.elapsed_s() < 10


def test_proc_stats_self():
    st = proc_stats()
    assert st["pid"] == os.getpid()
    assert st["rss_bytes"] > 1 << 20       # a python process is > 1 MiB
    assert st["utime_s"] >= 0
    if st.get("threads") is not None:
        assert st["threads"] >= 1


def test_proc_stats_other_pid():
    st = proc_stats(os.getppid())
    assert st["pid"] == os.getppid()


def test_backtrace_handlers_idempotent():
    assert install_backtrace_handlers()
    assert install_backtrace_handlers()   # second call: already active
    import faulthandler

    assert faulthandler.is_enabled()


def test_notifier_log_component_and_threshold(capsys):
    from ompi_tpu.core.config import var_registry
    from ompi_tpu.runtime.notifier import Severity, notify

    notify(Severity.ERROR, "test-event", "the details")
    err = capsys.readouterr().err
    assert "test-event" in err and "the details" in err
    # below threshold (default warn): silent
    notify(Severity.DEBUG, "invisible-event", "x")
    assert "invisible-event" not in capsys.readouterr().err


def test_mpiext_registry():
    from ompi_tpu.mpi import mpiext

    assert {"tpu", "device_heap", "sequence_parallel"} <= mpiext.extensions()
    # probes never raise; on the CPU test rig tpu probe is simply False/True
    assert mpiext.query_tpu_support() in (True, False)
    assert mpiext.query_sequence_parallel_support() is True
    assert mpiext.has_extension("no-such-ext") is False
    mpiext.register_extension("always", lambda: True)
    assert mpiext.has_extension("always") is True


def test_schizo_translates_mpirun_cli():
    from ompi_tpu.tools.schizo import translate_mpirun

    targv, env = translate_mpirun(
        ["-np", "4", "--mca", "coll", "host", "-x", "FOO=bar",
         "--machinefile", "hf", "--map-by", "node", "--bind-to", "core",
         "--timeout", "30",
         "--report-bindings", "./a.out", "arg1"])
    assert targv[:2] == ["-np", "4"]
    assert ["--mca", "coll", "host"] == targv[2:5]
    assert ["--hostfile", "hf"] == targv[5:7]
    assert ["--map-by", "bynode"] == targv[7:9]
    assert ["--timeout", "30"] == targv[9:11]
    assert targv[11:] == ["--", "./a.out", "arg1"]
    assert env == {"FOO": "bar"}


def test_schizo_rejects_unknown_option():
    from ompi_tpu.tools.schizo import translate_mpirun

    with pytest.raises(ValueError):
        translate_mpirun(["--definitely-not-a-flag", "x", "./a.out"])


def test_schizo_end_to_end_mpirun():
    env = dict(os.environ)
    env.pop("OMPI_TPU_RANK", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.schizo", "-np", "2",
         "-x", "SCHIZO_PROBE=42", "--",
         sys.executable, "-c",
         "import os, ompi_tpu\n"
         "comm = ompi_tpu.init()\n"
         "print(f'rank {comm.rank} sees {os.environ[\"SCHIZO_PROBE\"]}')\n"
         "ompi_tpu.finalize()\n"],
        capture_output=True, text=True, timeout=90, env=env, cwd=repo)
    assert r.returncode == 0, (r.stdout, r.stderr)
    for rank in range(2):
        assert f"rank {rank} sees 42" in r.stdout


def test_hwtopo_discover():
    from ompi_tpu.core.hwtopo import discover

    t = discover()
    assert t.logical_cpus >= 1
    assert 1 <= t.physical_cores <= t.logical_cpus
    assert t.packages >= 1
    assert 1 <= t.allowed_cpus <= t.logical_cpus
    assert t.smt >= 1
    assert t.accelerators == 0  # not probed by default


def test_ras_localhost_uses_topology():
    from ompi_tpu.core.hwtopo import discover
    from ompi_tpu.runtime.job import AppContext, Job
    from ompi_tpu.runtime import ras

    job = Job([AppContext(argv=["true"], np=1)])
    ras.allocate(job)
    assert job.nodes[0].slots >= max(1, discover().allowed_cpus)


def test_rtc_bind_child():
    import os

    from ompi_tpu.core.config import var_registry
    from ompi_tpu.runtime.rtc import bind_child

    assert bind_child(os.getpid(), 0) is None     # default: none
    var_registry.set("rtc_bind", "core")
    allowed = sorted(os.sched_getaffinity(0))
    try:
        cpu = bind_child(os.getpid(), 1)
        if len(allowed) < 2:
            assert cpu is None            # single-cpu host: no-op
        else:
            assert cpu == allowed[1 % len(allowed)]
            assert os.sched_getaffinity(0) == {cpu}
    finally:
        # restore INSIDE finally: a failed assert must not leave the
        # whole pytest process pinned to one cpu
        os.sched_setaffinity(0, set(allowed))
        var_registry.set("rtc_bind", "none")
