"""Tests for the typed config-variable registry (≈ mca_base_var tests)."""

import os

import pytest

from ompi_tpu.core import config
from ompi_tpu.core.config import (
    InfoLevel, Var, VarRegistry, VarSource, VarType, register_var,
)


def test_register_and_default():
    v = register_var("testfw", "alpha", VarType.INT, 42, "a test var")
    assert v.value == 42
    assert v.source == VarSource.DEFAULT
    assert config.get_var("testfw_alpha") == 42


def test_duplicate_registration_returns_existing():
    v1 = register_var("testfw", "dup", VarType.INT, 1)
    v2 = register_var("testfw", "dup", VarType.INT, 999)
    assert v1 is v2
    assert v2.value == 1


def test_env_overrides_default(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_MCA_testfw_beta", "7")
    reg = VarRegistry()
    v = reg.register(Var("testfw", "beta", VarType.INT, 0))
    assert v.value == 7
    assert v.source == VarSource.ENV


def test_cli_overrides_env(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_MCA_testfw_gamma", "7")
    reg = VarRegistry()
    reg.load_cli([("testfw_gamma", "9")])
    v = reg.register(Var("testfw", "gamma", VarType.INT, 0))
    assert v.value == 9
    assert v.source == VarSource.COMMAND_LINE


def test_cli_after_registration(monkeypatch):
    reg = VarRegistry()
    v = reg.register(Var("testfw", "late", VarType.INT, 0))
    reg.load_cli([("testfw_late", "5")])
    assert v.value == 5


def test_file_source(tmp_path, monkeypatch):
    conf = tmp_path / "params.conf"
    conf.write_text("# comment\ntestfw_filed = 13  # trailing\n")
    monkeypatch.setenv("OMPI_TPU_PARAM_FILE", str(conf))
    reg = VarRegistry()
    v = reg.register(Var("testfw", "filed", VarType.INT, 0))
    assert v.value == 13
    assert v.source == VarSource.FILE


def test_env_beats_file(tmp_path, monkeypatch):
    conf = tmp_path / "params.conf"
    conf.write_text("testfw_prec = 1\n")
    monkeypatch.setenv("OMPI_TPU_PARAM_FILE", str(conf))
    monkeypatch.setenv("OMPI_TPU_MCA_testfw_prec", "2")
    reg = VarRegistry()
    v = reg.register(Var("testfw", "prec", VarType.INT, 0))
    assert v.value == 2


def test_set_wins(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_MCA_testfw_sv", "2")
    reg = VarRegistry()
    v = reg.register(Var("testfw", "sv", VarType.INT, 0))
    reg.set("testfw_sv", 11)
    assert v.value == 11
    assert v.source == VarSource.SET


def test_size_parsing():
    reg = VarRegistry()
    v = reg.register(Var("testfw", "sz", VarType.SIZE, 0))
    reg.set("testfw_sz", "64K")
    assert v.value == 64 * 1024
    reg.set("testfw_sz", "2M")
    assert v.value == 2 * 1024 * 1024


def test_bool_parsing():
    reg = VarRegistry()
    v = reg.register(Var("testfw", "b", VarType.BOOL, False))
    for raw, want in [("1", True), ("no", False), ("on", True), ("false", False)]:
        reg.set("testfw_b", raw)
        assert v.value is want
    with pytest.raises(ValueError):
        reg.set("testfw_b", "maybe")


def test_string_list():
    reg = VarRegistry()
    v = reg.register(Var("testfw", "lst", VarType.STRING_LIST, []))
    reg.set("testfw_lst", "xla, host ,tuned")
    assert v.value == ["xla", "host", "tuned"]


def test_enumerator_check():
    reg = VarRegistry()
    reg.register(Var("testfw", "en", VarType.STRING, "a", enumerator=("a", "b")))
    with pytest.raises(ValueError):
        reg.set("testfw_en", "c")


def test_read_only():
    reg = VarRegistry()
    reg.register(Var("testfw", "ro", VarType.INT, 5, read_only=True))
    with pytest.raises(ValueError):
        reg.set("testfw_ro", 6)


def test_synonyms(monkeypatch):
    monkeypatch.setenv("OMPI_TPU_MCA_old_name", "3")
    reg = VarRegistry()
    v = reg.register(Var("testfw", "newname", VarType.INT, 0, synonyms=("old_name",)))
    # env lookup uses canonical name only; synonym works through pending/file/cli
    reg.load_cli([("old_name", "4")])
    assert reg.get("old_name") == 4
    assert v.value == 4


def test_dump_contains_vars():
    reg = VarRegistry()
    reg.register(Var("testfw", "dumped", VarType.INT, 5, description="hello"))
    text = reg.dump()
    assert "testfw_dumped" in text and "hello" in text


def test_info_levels_filter_dump():
    reg = VarRegistry()
    reg.register(Var("fw", "basic", VarType.INT, 1, info_level=InfoLevel.USER_BASIC))
    reg.register(Var("fw", "dev", VarType.INT, 1, info_level=InfoLevel.DEV_ALL))
    text = reg.dump(max_level=InfoLevel.USER_BASIC)
    assert "fw_basic" in text and "fw_dev" not in text
