"""Tests for the component/framework registry (≈ mca_base_components_select)."""

import pytest

from ompi_tpu.core import config
from ompi_tpu.core.mca import Component, ComponentError, Framework


def _mkfw(name):
    fw = Framework(name, "test framework")

    @fw.component
    class Low(Component):
        NAME = "low"
        PRIORITY = 10

    @fw.component
    class High(Component):
        NAME = "high"
        PRIORITY = 50

    @fw.component
    class Picky(Component):
        NAME = "picky"
        PRIORITY = 90

        def query(self, **ctx):
            return self.PRIORITY if ctx.get("special") else None

    return fw


def test_priority_selection():
    fw = _mkfw("tfw_sel")
    assert fw.select().NAME == "high"


def test_query_context_gating():
    fw = _mkfw("tfw_ctx")
    assert fw.select(special=True).NAME == "picky"
    assert fw.select(special=False).NAME == "high"


def test_select_all_ordering():
    fw = _mkfw("tfw_all")
    names = [c.NAME for c in fw.select_all(special=True)]
    assert names == ["picky", "high", "low"]


def test_include_directive():
    fw = _mkfw("tfw_inc")
    config.set_var("tfw_inc_", "low")
    assert fw.select().NAME == "low"


def test_exclude_directive():
    fw = _mkfw("tfw_exc")
    config.set_var("tfw_exc_", "^high")
    assert fw.select(special=True).NAME == "picky"
    config.set_var("tfw_exc_", "^high,picky")
    assert fw.select(special=True).NAME == "low"


def test_missing_requested_component_errors():
    fw = _mkfw("tfw_miss")
    config.set_var("tfw_miss_", "nonexistent")
    with pytest.raises(ComponentError):
        fw.select()


def test_duplicate_component_rejected():
    fw = Framework("tfw_dup")

    @fw.component
    class A(Component):
        NAME = "a"

    with pytest.raises(ComponentError):
        @fw.component
        class A2(Component):
            NAME = "a"


def test_lifecycle_hooks():
    fw = Framework("tfw_life")
    events = []

    @fw.component
    class C(Component):
        NAME = "c"
        PRIORITY = 1

        def open(self):
            events.append("open")

        def close(self):
            events.append("close")

    fw.open()
    fw.open()  # idempotent
    fw.close()
    assert events == ["open", "close"]


def test_no_component_available():
    fw = Framework("tfw_none")

    @fw.component
    class Decliner(Component):
        NAME = "d"

        def query(self, **ctx):
            return None

    with pytest.raises(ComponentError):
        fw.select()
