"""Tests for output streams and show_help aggregation."""

from ompi_tpu.core import config, output


def test_stream_verbosity_gating(capsys):
    st = output.get_stream("tst_stream")
    st.verbose(1, "hidden %d", 1)
    assert "hidden" not in capsys.readouterr().err
    config.set_var("output_tst_stream_verbose", 5)
    st.verbose(1, "shown %d", 2)
    assert "shown 2" in capsys.readouterr().err


def test_stream_identity_cached():
    assert output.get_stream("tst_same") is output.get_stream("tst_same")


def test_help_text_substitution():
    text = output.help_text(
        "mca", "component-not-found",
        framework="coll", components="zzz", available="xla, host")
    assert "coll" in text and "zzz" in text


def test_show_help_dedup(capsys):
    output.flush_help_counts()
    for _ in range(3):
        output.show_help("mca", "framework-no-selection", framework="pml")
    err = capsys.readouterr().err
    assert err.count("pml") == 1
    counts = output.flush_help_counts()
    assert ("mca", "framework-no-selection", 2) in counts


def test_show_help_missing_topic_does_not_raise(capsys):
    output.flush_help_counts()
    output.show_help("no-such-topic", "tag")
    assert "missing help text" in capsys.readouterr().err
