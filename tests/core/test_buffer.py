"""Tests for the buffer-location abstraction."""

import numpy as np
import pytest

from ompi_tpu.core.buffer import (
    BufferKind, BufferLocationError, classify, is_device, nbytes_of,
)


def test_host_kinds():
    assert classify(np.zeros(3)) == BufferKind.HOST
    assert classify(b"abc") == BufferKind.HOST
    assert classify(bytearray(2)) == BufferKind.HOST
    assert classify(3.0) == BufferKind.HOST


def test_device_kind():
    import jax.numpy as jnp

    x = jnp.zeros(4)
    assert classify(x) == BufferKind.DEVICE
    assert is_device(x)


def test_traced_kind():
    import jax

    seen = []

    @jax.jit
    def f(x):
        seen.append(classify(x))
        return x

    f(np.zeros(2, np.float32))
    assert seen == [BufferKind.TRACED]


def test_unknown_rejected():
    with pytest.raises(BufferLocationError):
        classify(object())


def test_nbytes():
    assert nbytes_of(np.zeros(4, np.float32)) == 16
    assert nbytes_of(b"12345") == 5
