"""netpatterns: shared comm-topology helpers (≈ ompi/patterns/net)."""

import pytest

from ompi_tpu.core.netpatterns import (binomial_children, binomial_parent,
                                       bruck_peers, kary_children,
                                       kary_parent, recursive_doubling_peers,
                                       tree_depth)


@pytest.mark.parametrize("k", [1, 2, 3, 4])
@pytest.mark.parametrize("n", [1, 2, 5, 16, 33])
def test_kary_tree_consistent(n, k):
    # every non-root has exactly one parent, and parent/child agree
    seen = set()
    for r in range(n):
        p = kary_parent(r, k)
        if r == 0:
            assert p is None
        else:
            assert 0 <= p < r
            assert r in kary_children(p, n, k)
        for c in kary_children(r, n, k):
            assert c not in seen
            seen.add(c)
    assert seen == set(range(1, n))


@pytest.mark.parametrize("n", [1, 2, 7, 8, 20])
def test_binomial_tree_consistent(n):
    seen = set()
    for r in range(n):
        p = binomial_parent(r)
        if r == 0:
            assert p is None
        else:
            assert p == r & (r - 1)
            assert r in binomial_children(p, n)
        for c in binomial_children(r, n):
            assert c not in seen
            seen.add(c)
    assert seen == set(range(1, n))


def test_binomial_known_shape():
    assert binomial_children(0, 8) == [1, 2, 4]
    assert binomial_children(4, 8) == [5, 6]
    assert binomial_children(6, 8) == [7]
    assert binomial_children(1, 8) == []


def test_recursive_doubling_and_bruck():
    assert recursive_doubling_peers(0, 8) == [1, 2, 4]
    assert recursive_doubling_peers(5, 8) == [4, 7, 1]
    # bruck rounds: log2-many (send, recv) pairs, distinct distances
    rounds = bruck_peers(3, 8)
    assert rounds == [(2, 4), (1, 5), ((3 - 4) % 8, 7)]


def test_tree_depth():
    assert tree_depth(1) == 0
    assert tree_depth(3, 2) == 1
    assert tree_depth(7, 2) == 2
    assert tree_depth(8, 2) == 3
    assert tree_depth(13, 3) == 2


def test_rml_tree_rides_netpatterns():
    from ompi_tpu.runtime.rml import tree_children, tree_parent

    assert tree_parent(0) is None
    assert tree_parent(5) == 2
    assert tree_children(1, 6) == [3, 4]
