"""Memchecker: buffer-validity checks at PML boundaries
(≈ opal/mca/memchecker/valgrind annotations, SURVEY.md §5)."""

import numpy as np
import pytest

from ompi_tpu.core.config import var_registry
from ompi_tpu.core.memchecker import (MemcheckError, check_send, enabled,
                                      prepare_recv)
from tests.mpi.harness import run_ranks


@pytest.fixture
def memcheck_on():
    var_registry.set("memchecker_enable", True)
    yield
    var_registry.set("memchecker_enable", False)


def test_disabled_by_default():
    assert not enabled()


def test_nan_send_rejected(memcheck_on):
    with pytest.raises(MemcheckError):
        check_send(np.array([1.0, np.nan]))
    check_send(np.array([1.0, 2.0]))          # clean floats pass
    check_send(np.array([1, 2], np.int32))    # ints never NaN-scan


def test_readonly_recv_rejected(memcheck_on):
    buf = np.zeros(4)
    buf.flags.writeable = False
    with pytest.raises(MemcheckError):
        prepare_recv(buf)


def test_recv_poisoned(memcheck_on):
    f = np.zeros(4)
    prepare_recv(f)
    assert np.isnan(f).all()
    i = np.zeros(4, np.int32)
    prepare_recv(i)
    assert (i.view(np.uint8) == 0xCC).all()


def test_end_to_end_via_pml(memcheck_on):
    def body(comm):
        if comm.rank == 0:
            with pytest.raises(Exception):
                comm.send(np.array([np.nan]), dest=1, tag=1)
            comm.send(np.array([1.0]), dest=1, tag=2)   # clean send works
        else:
            got = comm.recv(source=0, tag=2)
            assert float(got[0]) == 1.0
        return True

    assert all(run_ranks(2, body))
