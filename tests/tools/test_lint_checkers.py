"""ompi-lint checker proofs: every checker catches its bad fixture and
stays silent on a clean one.

Each fixture is a minimal tree written to tmp_path containing exactly
one violation of the invariant the checker owns, plus the registry /
dispatcher scaffolding the checker indexes.  The full-tree run at the
bottom is the acceptance gate: the real tree lints clean with an empty
baseline (the CI `lint` job re-asserts this on every push).
"""

import json
import subprocess
import sys

from tools.lint.baseline import Baseline
from tools.lint.checkers import (frame_op, lock_order, pmix_rpc,
                                 pvar_spec, reader_thread, rml_tag,
                                 span_pairing, var_registry)
from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex


def _tree(tmp_path, files: dict):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return ProjectIndex.build(str(tmp_path))


def _rules(findings):
    return {(f.rule, f.symbol) for f in findings}


# ---------------------------------------------------------------------------
# var-registry
# ---------------------------------------------------------------------------

_VAR_CLEAN = """
from config import register_var, var_registry

register_var("pml", "eager_limit", "size", 4096)
register_var("pml", "greeting", "string", "hi")

def use():
    var_registry.get("pml_eager_limit")
    s = var_registry.get("pml_greeting") or ""
    return s
"""

_VAR_CONFIG = """
class _Reg:
    def get(self, name):
        return None

def register_var(fw, name, vtype, default, **kw):
    pass

var_registry = _Reg()
"""


def test_var_registry_unregistered_read(tmp_path):
    idx = _tree(tmp_path, {
        "config.py": _VAR_CONFIG,
        "app.py": _VAR_CLEAN + """
def broken():
    return var_registry.get("pml_eager_limti")   # typo'd read
""",
    })
    got = _rules(var_registry.run(idx))
    assert ("unregistered-read", "pml_eager_limti") in got


def test_var_registry_type_mismatch_and_env(tmp_path):
    idx = _tree(tmp_path, {
        "config.py": _VAR_CONFIG,
        "app.py": _VAR_CLEAN + """
import os

def broken():
    n = int(var_registry.get("pml_greeting"))    # int() of a string var
    os.environ.get("OMPI_TPU_TYPOED_KNOB")       # never declared
    return n
""",
    })
    got = _rules(var_registry.run(idx))
    assert ("type-mismatch", "pml_greeting") in got
    assert ("unknown-env-read", "OMPI_TPU_TYPOED_KNOB") in got


def test_var_registry_clean(tmp_path):
    idx = _tree(tmp_path, {
        "config.py": _VAR_CONFIG,
        "app.py": _VAR_CLEAN + """
import os

ENV_KNOB = "OMPI_TPU_DECLARED_KNOB"

def fine():
    # declared-constant env read + dynamic read against a loop
    # registration
    os.environ.get(ENV_KNOB)
    for coll in ("bcast", "reduce"):
        register_var("coll", f"host_{coll}_algorithm", "string", "")
    which = "bcast"
    return var_registry.get(f"coll_host_{which}_algorithm")
""",
    })
    assert var_registry.run(idx) == []


# ---------------------------------------------------------------------------
# pvar-spec
# ---------------------------------------------------------------------------

_TRACE_MOD = """
_COUNTER_SPECS = (
    ("frames_sent_total", "frames", "sent"),
    ("frames_lost_total", "frames", "never bumped anywhere"),
)
counters = {n: 0 for n, _u, _d in _COUNTER_SPECS}

def count(name, delta=1):
    counters[name] += delta
"""


def test_pvar_spec_dead_and_undeclared(tmp_path):
    idx = _tree(tmp_path, {
        "trace.py": _TRACE_MOD,
        "app.py": """
from trace import count as _c  # noqa: F401 — bare import form
import trace as trace_mod

def hot_path():
    trace_mod.count("frames_sent_total")
    trace_mod.count("frames_dropped_total")   # not in _COUNTER_SPECS
""",
    })
    got = _rules(pvar_spec.run(idx))
    assert ("undeclared-counter", "frames_dropped_total") in got
    assert ("dead-pvar", "frames_lost_total") in got
    assert ("dead-pvar", "frames_sent_total") not in got


def test_pvar_spec_clean_with_fstring_bump(tmp_path):
    idx = _tree(tmp_path, {
        "trace.py": _TRACE_MOD.replace(
            '"never bumped anywhere"', '"bumped via f-string"'),
        "app.py": """
import trace as trace_mod

def hot_path(kind):
    trace_mod.count(f"frames_{kind}_total")   # matches both specs
""",
    })
    assert pvar_spec.run(idx) == []


def test_pvar_spec_agg_metrics_must_name_real_counters(tmp_path):
    """The aggregated-metric family (per-job sums on the DVM scrape
    endpoint) must stay in sync with _COUNTER_SPECS: a renamed counter
    still listed in AGG_METRICS is flagged, matching entries are not."""
    idx = _tree(tmp_path, {
        "trace.py": _TRACE_MOD,
        "app.py": """
import trace as trace_mod

def hot_path():
    trace_mod.count("frames_sent_total")
    trace_mod.count("frames_lost_total")
""",
        "metrics.py": """
AGG_METRICS = (
    "frames_sent_total",          # real counter — clean
    "frames_renamed_total",       # vanished from _COUNTER_SPECS — flag
)
""",
    })
    got = _rules(pvar_spec.run(idx))
    assert ("unknown-agg-metric", "frames_renamed_total") in got
    assert ("unknown-agg-metric", "frames_sent_total") not in got


_TRACE_HIST_MOD = """
_COUNTER_SPECS = (
    ("frames_sent_total", "frames", "sent"),
)
counters = {n: 0 for n, _u, _d in _COUNTER_SPECS}

def count(name, delta=1):
    counters[name] += delta

_HIST_SPECS = (
    ("coll_dispatch_ns", "nanoseconds", "dispatch latency"),
    ("pml_eager_ns", "nanoseconds", "eager latency"),
    ("io_write_ns", "nanoseconds", "never recorded anywhere"),
)
hists = {}

def record_hist(name, dur_ns, labels=""):
    hists.setdefault(name, [0])[0] += 1
"""


def test_pvar_spec_hist_dead_and_undeclared(tmp_path):
    """The _HIST_SPECS discipline, both directions: an undeclared
    record_hist name is flagged, a never-recorded spec is dead, and
    f-string names expand like counter bumps."""
    idx = _tree(tmp_path, {
        "trace.py": _TRACE_HIST_MOD,
        "app.py": """
import trace as trace_mod

def hot_path(proto):
    trace_mod.count("frames_sent_total")
    trace_mod.record_hist("coll_dispatch_ns", 5, labels='slot="bcast"')
    trace_mod.record_hist("made_up_ns", 5)        # not in _HIST_SPECS
    trace_mod.record_hist(f"pml_{proto}_ns", 5)   # matches pml_eager_ns
""",
    })
    got = _rules(pvar_spec.run(idx))
    assert ("undeclared-hist", "made_up_ns") in got
    assert ("dead-hist", "io_write_ns") in got
    assert ("dead-hist", "coll_dispatch_ns") not in got
    assert ("dead-hist", "pml_eager_ns") not in got   # f-string kept alive
    # histogram findings never bleed into the counter family
    assert not any(k == "undeclared-counter" for k, _ in got)


def test_pvar_spec_agg_hists_must_name_real_histograms(tmp_path):
    """AGG_HISTS (the per-job element-wise bucket sums on the scrape
    endpoint) cross-checks against _HIST_SPECS like AGG_METRICS does
    against _COUNTER_SPECS."""
    idx = _tree(tmp_path, {
        "trace.py": _TRACE_HIST_MOD,
        "app.py": """
import trace as trace_mod

def hot_path():
    trace_mod.count("frames_sent_total")
    trace_mod.record_hist("coll_dispatch_ns", 5)
    trace_mod.record_hist("pml_eager_ns", 5)
    trace_mod.record_hist("io_write_ns", 5)
""",
        "metrics.py": """
AGG_HISTS = (
    "coll_dispatch_ns",        # real histogram — clean
    "coll_renamed_ns",         # vanished from _HIST_SPECS — flag
)
""",
    })
    got = _rules(pvar_spec.run(idx))
    assert ("unknown-agg-hist", "coll_renamed_ns") in got
    assert ("unknown-agg-hist", "coll_dispatch_ns") not in got


# ---------------------------------------------------------------------------
# rml-tag
# ---------------------------------------------------------------------------

_BUS = """
TAG_GOOD = "good"
TAG_ORPHAN_SEND = "orphan_send"
TAG_DEAD = "dead"
TAG_UNSENT = "unsent"

class Node:
    def register_recv(self, tag, cb):
        pass
    def xcast(self, tag, payload):
        pass
    def send_up(self, tag, payload):
        pass
"""


def test_rml_tag_findings(tmp_path):
    idx = _tree(tmp_path, {
        "rml.py": _BUS,
        "daemon.py": """
import rml

def wire(node):
    node.register_recv(rml.TAG_GOOD, lambda o, p: None)
    node.register_recv(rml.TAG_UNSENT, lambda o, p: None)
    node.xcast(rml.TAG_GOOD, 1)
    node.send_up(rml.TAG_ORPHAN_SEND, 2)        # nobody registers it
    node.xcast(rml.TAG_TYPO, 3)                 # not defined on the bus
""",
    })
    got = _rules(rml_tag.run(idx))
    assert ("unhandled-send", "TAG_ORPHAN_SEND") in got
    assert ("dead-tag", "TAG_DEAD") in got
    assert ("unsent-handler", "TAG_UNSENT") in got
    assert ("unknown-tag", "TAG_TYPO") in got
    assert ("unhandled-send", "TAG_GOOD") not in got


def test_rml_tag_ignores_non_bus_tag_namespaces(tmp_path):
    idx = _tree(tmp_path, {
        "rml.py": _BUS,
        "coll.py": "TAG_BARRIER = -4242\nTAG_BCAST = -4243\n",
        "daemon.py": """
import rml

def wire(node):
    node.register_recv(rml.TAG_GOOD, lambda o, p: None)
    node.register_recv(rml.TAG_UNSENT, lambda o, p: None)
    node.register_recv(rml.TAG_DEAD, lambda o, p: None)
    node.xcast(rml.TAG_GOOD, 1)
    node.xcast(rml.TAG_UNSENT, 1)
    node.xcast(rml.TAG_ORPHAN_SEND, 1)
    node.register_recv(rml.TAG_ORPHAN_SEND, lambda o, p: None)
    node.xcast(rml.TAG_DEAD, 1)
""",
    })
    # the coll p2p tag space must not be reported as dead bus tags
    assert rml_tag.run(idx) == []


# ---------------------------------------------------------------------------
# frame-op
# ---------------------------------------------------------------------------

_DISPATCH = """
class Pml:
    def _on_frame(self, peer, hdr, payload):
        t = hdr["t"]
        if t in ("eager", "rndv"):
            pass
        elif t == "ft":
            FT().on_ft_frame(peer, hdr)
        elif t == "ghost":
            pass                      # nothing ever emits this
        else:
            pass

class FT:
    def on_ft_frame(self, peer, hdr):
        op = hdr.get("op")
        if op == "beat":
            pass
        else:
            pass
"""


def test_frame_op_unhandled_and_dead(tmp_path):
    idx = _tree(tmp_path, {
        "pml.py": _DISPATCH,
        "send.py": """
def send(q, big):
    hdr = {"cid": 0}
    hdr["t"] = "rndv" if big else "eager"
    q.append(hdr)
    q.append({"t": "ft", "op": "beat"})
    q.append({"t": "ft", "op": "gossip2"})   # no dispatch branch
    q.append({"t": "mystery"})               # no dispatch branch
""",
    })
    got = _rules(frame_op.run(idx))
    assert ("unhandled-op", "ft:gossip2") in got
    assert ("unhandled-op", "pml:mystery") in got
    assert ("unemitted-branch", "pml:ghost") in got
    assert ("unhandled-op", "pml:rndv") not in got   # IfExp emission seen


def test_frame_op_ft_subscript_and_update_emission(tmp_path):
    """FT ops emitted as ``hdr["op"] = …`` / ``hdr.update(op=…)`` are
    ft-plane emissions (the "op" key only exists on t="ft" frames):
    a dispatched op emitted this way is NOT a dead branch, and an
    undispatched one IS an unhandled op."""
    idx = _tree(tmp_path, {
        "pml.py": _DISPATCH.replace(
            '        elif t == "ghost":\n            pass'
            '                      # nothing ever emits this\n', ""),
        "send.py": """
def send(q):
    q.append({"t": "eager"})
    q.append({"t": "rndv"})
    hdr = {"t": "ft"}
    hdr["op"] = "beat"              # subscript-assign emission
    q.append(hdr)
    h2 = {"t": "ft"}
    h2.update(op="gossip2")         # update-kwarg emission, no branch
    q.append(h2)
""",
    })
    got = _rules(frame_op.run(idx))
    assert ("unemitted-branch", "ft:beat") not in got
    assert ("unhandled-op", "ft:gossip2") in got


def test_frame_op_clean(tmp_path):
    idx = _tree(tmp_path, {
        "pml.py": _DISPATCH.replace(
            '        elif t == "ghost":\n            pass'
            '                      # nothing ever emits this\n', ""),
        "send.py": """
def send(q, big):
    hdr = {"cid": 0}
    hdr.update(t="rndv" if big else "eager")
    q.append(hdr)
    q.append({"t": "ft", "op": "beat"})
""",
    })
    assert frame_op.run(idx) == []


# ---------------------------------------------------------------------------
# pmix-rpc
# ---------------------------------------------------------------------------

_PMIX = """
class Server:
    def _handle(self, cmd, args):
        if cmd == "put":
            rank, key, value = args
            return ("ok",)
        if cmd == "report":
            reporter, failed = args[:2]
            inc = int(args[2]) if len(args) > 2 else 0
            return ("ok", inc)
        if cmd == "dead_arm":
            return ("ok",)
        raise RuntimeError(cmd)

class Client:
    def _rpc(self, *msg):
        return ("ok",)
"""


def test_pmix_rpc_findings(tmp_path):
    idx = _tree(tmp_path, {
        "pmix.py": _PMIX + """
class App(Client):
    def put(self, k, v):
        self._rpc("put", 0, k, v)
    def put_legacy(self):
        self._rpc("put", 0)              # server unpacks three
    def report(self):
        self._rpc("report", 1, 2)        # 3rd arg is len-guarded: fine
    def ping(self):
        self._rpc("ping")                # no server branch
""",
    })
    got = _rules(pmix_rpc.run(idx))
    assert ("unknown-rpc", "ping") in got
    assert ("arity-mismatch", "put") in got
    assert ("dead-rpc", "dead_arm") in got
    assert ("arity-mismatch", "report") not in got


def test_pmix_rpc_clean(tmp_path):
    idx = _tree(tmp_path, {
        "pmix.py": _PMIX.replace(
            '        if cmd == "dead_arm":\n'
            '            return ("ok",)\n', "") + """
class App(Client):
    def put(self, k, v):
        self._rpc("put", 0, k, v)
    def report(self, inc=None):
        if inc is None:
            self._rpc("report", 1, 2)
        else:
            self._rpc("report", 1, 2, inc)
""",
    })
    assert pmix_rpc.run(idx) == []


def test_pmix_rpc_guarded_tuple_unpack_optional(tmp_path):
    """A tuple-unpack of args under a len(args) guard is the legacy-
    fallback pattern — a short legacy client call is not a mismatch."""
    idx = _tree(tmp_path, {"pmix.py": """
class Server:
    def _handle(self, cmd, args):
        if cmd == "hello":
            if len(args) >= 2:
                rank, inc = args
            else:
                rank, inc = args[0], 0
            return ("ok", rank, inc)
        raise RuntimeError(cmd)

class Client:
    def _rpc(self, *msg):
        return ("ok",)

class App(Client):
    def hello_modern(self):
        self._rpc("hello", 3, 7)
    def hello_legacy(self):
        self._rpc("hello", 3)
"""})
    assert pmix_rpc.run(idx) == []


def test_var_registry_frameworkless_name(tmp_path):
    """Var.full_name keys on FRAMEWORK truthiness: register_var('',
    'standalone', …) answers reads of 'standalone', not '_standalone'."""
    idx = _tree(tmp_path, {
        "config.py": _VAR_CONFIG,
        "app.py": """
from config import register_var, var_registry

register_var("", "standalone", "bool", False)

def use():
    return var_registry.get("standalone")
""",
    })
    assert var_registry.run(idx) == []


# ---------------------------------------------------------------------------
# reader-thread
# ---------------------------------------------------------------------------

_READER = """
import time

class PMIxClient:
    def _rpc(self, *msg):
        return ("ok",)
    def report_failed(self, rank, reason=""):
        return self._rpc("report_failed", rank, reason)

class Btl:
    def __init__(self, client):
        self.client = client
    def _read_loop(self, sock):
        while True:
            self._dispatch(sock)
    def _dispatch(self, frame):
        self._declare(1)
    def _declare(self, peer):
        self.client.report_failed(peer, "gossip")    # RPC on the reader!
"""


def test_reader_thread_rpc_detected(tmp_path):
    idx = _tree(tmp_path, {"btl.py": _READER})
    got = reader_thread.run(idx)
    assert any(f.rule == "rpc-on-reader" for f in got), got


def test_reader_thread_register_recv_callback_and_sleep(tmp_path):
    idx = _tree(tmp_path, {"node.py": """
import time

class Node:
    def register_recv(self, tag, cb):
        pass

class Daemon:
    def wire(self, node):
        node.register_recv("launch", self._on_launch)
    def _on_launch(self, origin, payload):
        time.sleep(1.0)        # blocking a link reader thread
"""})
    got = reader_thread.run(idx)
    assert any(f.rule == "sleep-on-reader"
               and "Daemon._on_launch" in f.message for f in got), got


def test_reader_thread_lambda_callback_and_hook_attr(tmp_path):
    """Lambda-wrapped register_recv callbacks and reader hook
    attributes (on_peer_lost) are entry points too — the adapter form
    must not hide a blocking handler from the checker."""
    idx = _tree(tmp_path, {"node.py": """
import time

class Node:
    def register_recv(self, tag, cb):
        pass

class Daemon:
    def wire(self, node):
        node.register_recv("exit", lambda o, p: self._on_exit(o, p))
        node.on_peer_lost = self._on_lost
    def _on_exit(self, origin, payload):
        time.sleep(0.5)              # blocking the link reader
    def _on_lost(self, peer):
        import subprocess
        subprocess.run(["true"])     # blocking the link reader
"""})
    got = {f.rule for f in reader_thread.run(idx)}
    assert "sleep-on-reader" in got and "subprocess-on-reader" in got


def test_reader_thread_bare_import_sinks(tmp_path):
    """`from time import sleep` / `from subprocess import run` must
    not bypass the sink detection."""
    idx = _tree(tmp_path, {"node.py": """
from time import sleep
from subprocess import run

class Node:
    def register_recv(self, tag, cb):
        pass

class Daemon:
    def wire(self, node):
        node.register_recv("x", self._on_x)
        node.register_recv("y", self._on_y)
    def _on_x(self, origin, payload):
        sleep(1.0)
    def _on_y(self, origin, payload):
        run(["true"])
"""})
    got = {f.rule for f in reader_thread.run(idx)}
    assert "sleep-on-reader" in got and "subprocess-on-reader" in got


def test_reader_thread_clean_handoff(tmp_path):
    idx = _tree(tmp_path, {"btl.py": _READER.replace(
        'self.client.report_failed(peer, "gossip")    # RPC on the reader!',
        "self.pending = peer    # queued; the gossip loop drains it")})
    assert reader_thread.run(idx) == []


def test_reader_thread_waiver_comment(tmp_path):
    idx = _tree(tmp_path, {"btl.py": _READER.replace(
        'self.client.report_failed(peer, "gossip")    # RPC on the reader!',
        'self.client.report_failed(peer, "g")  # lint: reader-ok')})
    assert reader_thread.run(idx) == []


def test_reader_thread_native_park_approved_in_poll_loop(tmp_path):
    """A GIL-released native park (arena.c's wait entry points) is THE
    approved blocking form for a poll/read loop's idle window — even
    through a helper hop — while a python time.sleep on the same new
    path stays flagged."""
    idx = _tree(tmp_path, {"btl.py": """
import time

class Btl:
    def _poll_loop(self):
        while True:
            if not self._native_park():
                time.sleep(0)          # loop's own pacing: exempt
    def _native_park(self):
        ex = self._lib
        return ex.ompi_tpu_ring_wait_any(0, 0, 1, 64, 1000000) >= 0
"""})
    assert reader_thread.run(idx) == []


def test_reader_thread_native_park_flagged_on_frame_dispatch(tmp_path):
    """The same park reached from a frame-dispatch entry is a finding:
    blocking _on_frame stalls every peer behind one wait."""
    idx = _tree(tmp_path, {"pml.py": """
class Pml:
    def _on_frame(self, peer, header, payload):
        self._wait_peer(header)
    def _wait_peer(self, header):
        self._lib.ompi_tpu_arena_wait(0, 1, 2, 64, 1000000)
"""})
    got = reader_thread.run(idx)
    assert any(f.rule == "park-on-reader"
               and "Pml._on_frame" in f.message for f in got), got


def test_reader_thread_net_park_approved_in_poll_loop(tmp_path):
    """net.c's bounded network parks (poll / recv_into / writev) carry
    the same approval as the arena waits: fine on a *_loop thread."""
    idx = _tree(tmp_path, {"btl.py": """
class Btl:
    def _poll_loop(self):
        while True:
            n = self._net.ompi_tpu_net_poll(0, 2, 0, 100, 50000000)
            if n > 0:
                self._net.ompi_tpu_net_recv_into(3, 0, 4096, 1000000)
"""})
    assert reader_thread.run(idx) == []


def test_reader_thread_net_park_flagged_on_frame_dispatch(tmp_path):
    """The same network park reached from a frame-dispatch callback is
    a finding: one peer's slow socket stalls every other peer."""
    idx = _tree(tmp_path, {"pml.py": """
class Pml:
    def _on_frame(self, peer, header, payload):
        self._net.ompi_tpu_net_writev(3, 0, 2, 20000000)
"""})
    got = reader_thread.run(idx)
    assert any(f.rule == "park-on-reader"
               and "Pml._on_frame" in f.message for f in got), got


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

def test_lock_order_ab_ba_cycle(tmp_path):
    idx = _tree(tmp_path, {"mpi/locks.py": """
import threading

class A:
    def __init__(self, b):
        self._lock = threading.Lock()
        self.b = b
    def outer_ab(self):
        with self._lock:
            self.b.inner_b()
    def inner_a(self):
        with self._lock:
            return 1

class B:
    def __init__(self, a):
        self._block = threading.Lock()
        self.a = a
    def outer_ba(self):
        with self._block:
            self.a.inner_a()
    def inner_b(self):
        with self._block:
            return 2
"""})
    got = lock_order.run(idx)
    assert any(f.rule == "cycle" for f in got), got


def test_lock_order_rpc_under_reader_shared_lock(tmp_path):
    idx = _tree(tmp_path, {"mpi/pml.py": """
import threading

class PMIxClient:
    def _rpc(self, *m):
        return ("ok",)
    def report_failed(self, r):
        return self._rpc("report_failed", r)

class Pml:
    def __init__(self, client):
        self._lock = threading.Lock()
        self.client = client
    def _read_loop(self, sock):
        self.on_frame(sock)
    def on_frame(self, frame):
        with self._lock:          # reader-shared lock…
            self.client.report_failed(0)   # …held across an RPC
"""})
    got = lock_order.run(idx)
    assert any(f.rule == "rpc-under-lock" for f in got), got


def test_lock_order_three_lock_cycle(tmp_path):
    """A→B→C→A: the SCC has no edge between its two lowest-sorted
    members, so the reporter must pick any existing in-SCC edge."""
    idx = _tree(tmp_path, {"mpi/locks.py": """
import threading

class A:
    def __init__(self):
        self._alock = threading.Lock()
    def grab_ab(self, b):
        with self._alock:
            b.grab_b()

class B:
    def __init__(self):
        self._block = threading.Lock()
    def grab_b(self):
        with self._block:
            return 1
    def grab_bc(self, c):
        with self._block:
            c.grab_c()

class C:
    def __init__(self):
        self._clock = threading.Lock()
    def grab_c(self):
        with self._clock:
            return 2
    def grab_ca(self, a):
        with self._clock:
            with a._alock:
                return 3
"""})
    got = [f for f in lock_order.run(idx) if f.rule == "cycle"]
    assert len(got) == 1 and "A._alock" in got[0].symbol, got


def test_reader_thread_closure_handoff_not_attributed(tmp_path):
    """The approved hand-off: a reader handler spawning a thread whose
    CLOSURE sleeps must not be flagged — the closure runs on the new
    thread's stack, not the reader's."""
    idx = _tree(tmp_path, {"node.py": """
import threading
import time

class Node:
    def register_recv(self, tag, cb):
        pass

class Daemon:
    def wire(self, node):
        node.register_recv("launch", self._on_launch)
    def _on_launch(self, origin, payload):
        def worker():
            time.sleep(5.0)     # fine: another thread's stack
        threading.Thread(target=worker, daemon=True).start()
"""})
    assert reader_thread.run(idx) == []


def test_lock_order_cycle_through_mutual_recursion(tmp_path):
    """Locks acquired inside a call CYCLE must still reach the
    transitive sets (a memoized DFS with a cycle guard used to hide
    them, reporting a clean tree on a real inversion)."""
    idx = _tree(tmp_path, {"mpi/locks.py": """
import threading

class A:
    def __init__(self, b):
        self._alock = threading.Lock()
        self.b = b
    def hold_a_then_f(self):
        with self._alock:
            self.rec_f()
    def rec_f(self):
        self.rec_g()
    def rec_g(self):
        self.b.take_block()     # cycle member acquires B's lock
        self.rec_f()
    def take_alock(self):
        with self._alock:
            return 1

class B:
    def __init__(self, a):
        self._block = threading.Lock()
        self.a = a
    def take_block(self):
        with self._block:
            return 1
    def hold_b_then_a(self):
        with self._block:
            self.a.take_alock()
"""})
    got = [f for f in lock_order.run(idx) if f.rule == "cycle"]
    assert len(got) == 1, got


def test_lock_order_second_sleep_under_lock_detected(tmp_path):
    """A sleep OUTSIDE the lock must not shadow a later sleep INSIDE
    it (the single-site sink map used to compare against the first
    recorded site only)."""
    idx = _tree(tmp_path, {"mpi/pml.py": """
import threading
import time

class Pml:
    def __init__(self):
        self._lock = threading.Lock()
    def _read_loop(self, sock):
        self.on_frame(sock)
    def on_frame(self, frame):
        time.sleep(0.01)          # fine: lock not held
        with self._lock:          # reader-shared
            time.sleep(0.5)       # NOT fine
"""})
    got = [f for f in lock_order.run(idx)
           if f.rule == "sleep-under-lock"]
    assert len(got) == 1, got


def test_baseline_write_merges_justifications(tmp_path):
    path = str(tmp_path / "bl.json")
    f1 = Finding("rml-tag", "dead-tag", "TAG_X", "m")
    f2 = Finding("lock-order", "cycle", "A->B", "m")
    Baseline.write(path, [f1, f2])
    # hand-edit a justification
    doc = json.loads(open(path).read())
    for ent in doc["findings"]:
        if ent["fingerprint"] == f2.fingerprint:
            ent["justification"] = "accepted: bounded by X"
    open(path, "w").write(json.dumps(doc))
    # re-write from an rml-tag-only run: the lock-order entry AND the
    # hand-written justification must both survive
    Baseline.write(path, [f1], keep=Baseline.load(path).entries)
    bl = Baseline.load(path)
    assert bl.entries[f2.fingerprint] == "accepted: bounded by X"
    assert f1.fingerprint in bl.entries


def test_lock_order_closure_with_not_attributed(tmp_path):
    """A closure's `with` runs on the closure's (spawned) stack — it
    must not fabricate an acquisition edge from the enclosing with-
    block, even when a legitimate reverse nesting exists elsewhere."""
    idx = _tree(tmp_path, {"mpi/locks.py": """
import threading

class A:
    def __init__(self, b):
        self._alock = threading.Lock()
        self.b = b
    def spawn_under_a(self):
        with self._alock:
            def worker():
                with self.b._block:     # another thread's stack
                    pass
            threading.Thread(target=worker, daemon=True).start()

class B:
    def __init__(self, a):
        self._block = threading.Lock()
        self.a = a
    def hold_b_then_a(self):
        with self._block:
            with self.a._alock:         # the one true order: B -> A
                pass
"""})
    assert [f for f in lock_order.run(idx) if f.rule == "cycle"] == []


def test_lock_order_ordered_nesting_clean(tmp_path):
    idx = _tree(tmp_path, {"mpi/locks.py": """
import threading

class Outer:
    def __init__(self, inner):
        self._lock = threading.Lock()
        self.inner = inner
    def work(self):
        with self._lock:
            self.inner.poke()

class Inner:
    def __init__(self):
        self._ilock = threading.Lock()
    def poke(self):
        with self._ilock:
            return 1
"""})
    assert lock_order.run(idx) == []


# ---------------------------------------------------------------------------
# span-pairing
# ---------------------------------------------------------------------------

_SPAN_TRACE = """
def coll_post(rank, cid, kind, sig, provider, nbytes):
    return 1

def coll_done(rank, cid, seq, kind):
    pass

def coll_err(rank, cid, seq, kind, err):
    pass

def begin():
    return 1

def complete(cat, name, t0, **args):
    pass

def record_hist(name, dur_ns, labels=""):
    pass
"""


def test_span_pairing_unpaired_post_and_begin(tmp_path):
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "coll.py": """
import trace as trace_mod

def run(comm):
    seq = trace_mod.coll_post(0, 1, "bcast", 0, "host", 64)
    return seq                        # never retired anywhere

def timed():
    t0 = trace_mod.begin()
    return t0                         # span never closed
""",
    })
    got = _rules(span_pairing.run(idx))
    assert ("unpaired-post", "coll.run") in got
    assert ("unmatched-begin", "coll.timed") in got


def test_span_pairing_missing_err_path(tmp_path):
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "coll.py": """
import trace as trace_mod

def run(comm, fn):
    seq = trace_mod.coll_post(0, 1, "bcast", 0, "host", 64)
    ret = fn(comm)                    # a raise here leaks the op
    trace_mod.coll_done(0, 1, seq, "bcast")
    return ret
""",
    })
    got = _rules(span_pairing.run(idx))
    assert ("no-err-path", "coll.run") in got
    assert not any(r == "unpaired-post" for r, _ in got)


def test_span_pairing_clean_try_except(tmp_path):
    """The canonical choke-point shape: post, body in try, done on the
    success path, err in the except — no findings."""
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "coll.py": """
import trace as trace_mod

def run(comm, fn):
    seq = trace_mod.coll_post(0, 1, "bcast", 0, "host", 64)
    t0 = trace_mod.begin()
    try:
        ret = fn(comm)
        trace_mod.coll_done(0, 1, seq, "bcast")
        return ret
    except BaseException as e:
        trace_mod.coll_err(0, 1, seq, "bcast", type(e).__name__)
        raise
    finally:
        trace_mod.complete("coll", "bcast", t0)
""",
    })
    assert span_pairing.run(idx) == []


def test_span_pairing_class_scope_pairing(tmp_path):
    """The nonblocking-request shape: post in __init__, done/err in the
    completion callbacks of the SAME class — clean.  A second class
    posting with no retirement anywhere still flags."""
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "nbc.py": """
import trace as trace_mod

class Request:
    def __init__(self, comm):
        self.seq = trace_mod.coll_post(0, 1, "ibcast", 0, "host", 64)
    def _on_complete(self):
        trace_mod.coll_done(0, 1, self.seq, "ibcast")
    def _on_error(self, e):
        trace_mod.coll_err(0, 1, self.seq, "ibcast", type(e).__name__)
""",
        "leaky.py": """
import trace as trace_mod

class Leaky:
    def start(self):
        self.seq = trace_mod.coll_post(0, 1, "x", 0, "host", 0)
""",
    })
    got = _rules(span_pairing.run(idx))
    assert ("unpaired-post", "leaky.start") in got
    assert not any(sym.startswith("nbc.") for _r, sym in got)


def test_span_pairing_module_scope_and_hist_closer(tmp_path):
    """begin() consumed by a complete() in ANOTHER class of the same
    module (the pml recv-state shape) is clean, and record_hist counts
    as a begin closer (pure-histogram timing stamps)."""
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "pml.py": """
import trace as trace_mod

class _RecvState:
    def __init__(self):
        self.trace_t0 = trace_mod.begin()

class Pml:
    def _finish(self, state):
        trace_mod.complete("pml", "recv", state.trace_t0)
""",
        "hist.py": """
import trace as trace_mod

def timed_write(fn):
    t0 = trace_mod.begin()
    fn()
    trace_mod.record_hist("io_write_ns", t0)
""",
    })
    assert span_pairing.run(idx) == []


def test_span_pairing_waiver_and_closure_retirement(tmp_path):
    """`# lint: span-ok` silences the opener, and a done inside a
    nested closure is part of the enclosing function's subtree."""
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "app.py": """
import trace as trace_mod

def fire_and_forget():
    trace_mod.coll_post(0, 1, "probe", 0, None, 0)  # lint: span-ok

def deferred(comm, schedule):
    seq = trace_mod.coll_post(0, 1, "ibarrier", 0, "host", 0)
    def on_done(e=None):
        if e is None:
            trace_mod.coll_done(0, 1, seq, "ibarrier")
        else:
            trace_mod.coll_err(0, 1, seq, "ibarrier", type(e).__name__)
    schedule(on_done)
""",
    })
    assert span_pairing.run(idx) == []


def test_span_pairing_ignores_lookalike_receivers(tmp_path):
    """str.count-style lookalikes: begin/complete on a non-trace
    receiver must not register as recorder calls."""
    idx = _tree(tmp_path, {
        "trace.py": _SPAN_TRACE,
        "app.py": """
import trace as trace_mod

def fine(editor, comm):
    editor.begin()                    # not the recorder
    seq = trace_mod.coll_post(0, 1, "bcast", 0, "host", 0)
    trace_mod.coll_done(0, 1, seq, "bcast")
    trace_mod.coll_err(0, 1, seq, "bcast", "X")
""",
    })
    assert span_pairing.run(idx) == []


# ---------------------------------------------------------------------------
# baseline mechanics + the acceptance gate
# ---------------------------------------------------------------------------

def test_baseline_split(tmp_path):
    f1 = Finding("rml-tag", "dead-tag", "TAG_X", "m")
    f2 = Finding("rml-tag", "dead-tag", "TAG_Y", "m")
    path = tmp_path / "baseline.json"
    Baseline.write(str(path), [f1])
    bl = Baseline.load(str(path))
    new, old, stale = bl.split([f1, f2])
    assert new == [f2] and old == [f1] and stale == []
    # a fixed finding leaves a stale entry behind → must fail the run
    new, old, stale = bl.split([f2])
    assert stale == [f1.fingerprint]
    doc = json.loads(path.read_text())
    assert doc["findings"][0]["fingerprint"] == f1.fingerprint


def test_driver_grandfather_and_stale(tmp_path):
    """End-to-end driver run: a finding grandfathered via
    --write-baseline stops failing the run; fixing it WITHOUT removing
    the entry fails again (stale), and staleness is global — other
    checkers must not re-report the entry as theirs."""
    from tools.lint.driver import _repo_root

    (tmp_path / "bus.py").write_text(
        'TAG_LOST = "lost"\n\n'
        "class Node:\n"
        "    def register_recv(self, tag, cb):\n"
        "        pass\n"
        "    def xcast(self, tag, payload):\n"
        "        pass\n\n"
        "def go(n):\n"
        "    n.xcast(TAG_LOST, 1)\n")
    bl = str(tmp_path / "bl.json")

    def lint(*extra):
        return subprocess.run(
            [sys.executable, "-m", "tools.lint", "--root",
             str(tmp_path), "--baseline", bl, *extra],
            capture_output=True, text=True, timeout=120,
            cwd=_repo_root())

    assert lint().returncode == 4          # rml-tag bit
    assert lint("--write-baseline").returncode == 0
    proc = lint()
    assert proc.returncode == 0, proc.stdout   # grandfathered
    assert "grandfathered" in proc.stdout
    # "fix" the finding but leave the baseline entry → stale, fails
    (tmp_path / "bus.py").write_text(
        "class Node:\n"
        "    def register_recv(self, tag, cb):\n"
        "        pass\n"
        "    def xcast(self, tag, payload):\n"
        "        pass\n")
    proc = lint()
    assert proc.returncode == 4 and "stale" in proc.stdout
    assert proc.stdout.count("stale baseline entry") == 1


def test_root_run_ignores_repo_baseline(tmp_path):
    """A --root fixture run without --baseline must see an EMPTY
    baseline — the repo's entries must neither grandfather fixture
    findings nor read as stale."""
    from tools.lint.driver import _repo_root

    (tmp_path / "clean.py").write_text("def f():\n    return 1\n")
    # repo baseline temporarily non-empty would be needed for the full
    # repro; here assert the clean fixture exits 0 regardless of the
    # repo baseline contents and without touching it
    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--root", str(tmp_path),
         "-q"],
        capture_output=True, text=True, timeout=120, cwd=_repo_root())
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "stale" not in proc.stdout


def test_full_tree_lints_clean():
    """The acceptance gate: the real tree, every checker, empty
    baseline, exit 0 — run exactly as CI runs it."""
    from tools.lint.driver import _repo_root

    proc = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--no-mypy", "-q"],
        capture_output=True, text=True, timeout=300, cwd=_repo_root())
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# hang-doctor protocol shapes (TAG_DOCTOR wiring + the doctor RPCs):
# the registries must hold the new protocol in BOTH directions
# ---------------------------------------------------------------------------

def test_rml_tag_doctor_wiring_clean_and_reply_must_be_handled(tmp_path):
    bus = _BUS.replace("TAG_ORPHAN_SEND", "TAG_DOCTOR").replace(
        "TAG_DEAD", "TAG_DOCTOR_REPLY").replace("TAG_UNSENT", "TAG_AUX")
    wired = """
import rml

def wire(node):
    node.register_recv(rml.TAG_GOOD, lambda o, p: None)
    node.xcast(rml.TAG_GOOD, 1)
    node.register_recv(rml.TAG_AUX, lambda o, p: None)
    node.xcast(rml.TAG_AUX, 1)
    node.xcast(rml.TAG_DOCTOR, 1)                 # HNP capture fan-out
    node.register_recv(rml.TAG_DOCTOR, lambda o, p: None)   # orted
    node.send_up(rml.TAG_DOCTOR_REPLY, (0, 1, []))          # orted
    node.register_recv(rml.TAG_DOCTOR_REPLY, lambda o, p: None)  # HNP
"""
    assert rml_tag.run(_tree(tmp_path, {"rml.py": bus,
                                        "daemon.py": wired})) == []
    # drop the HNP-side reply handler: the capture silently vanishes —
    # exactly the class the unhandled-send rule exists for
    broken = wired.replace(
        "    node.register_recv(rml.TAG_DOCTOR_REPLY, "
        "lambda o, p: None)  # HNP\n", "")
    got = _rules(rml_tag.run(_tree(tmp_path / "b", {"rml.py": bus,
                                                    "daemon.py": broken})))
    assert ("unhandled-send", "TAG_DOCTOR_REPLY") in got


def test_pmix_rpc_doctor_branches_need_callers_and_arity(tmp_path):
    pmix_src = _PMIX.replace(
        '        if cmd == "dead_arm":\n            return ("ok",)\n',
        '        if cmd == "doctor":\n'
        '            rank, port = int(args[0]), int(args[1])\n'
        '            return ("ok",)\n'
        '        if cmd == "doctor_ports":\n'
        '            return ("ok", {})\n')
    clean = pmix_src + """
class App(Client):
    def put(self, k, v):
        self._rpc("put", 0, k, v)
    def report(self):
        self._rpc("report", 1, 2)
    def register_doctor(self, port):
        self._rpc("doctor", 0, port)
    def doctor_ports(self):
        return self._rpc("doctor_ports")
"""
    assert pmix_rpc.run(_tree(tmp_path, {"pmix.py": clean})) == []
    # a client registering with too few args is the per-call ValueError
    # class; an uncalled branch is dead protocol
    broken = pmix_src + """
class App(Client):
    def put(self, k, v):
        self._rpc("put", 0, k, v)
    def report(self):
        self._rpc("report", 1, 2)
    def register_doctor(self):
        self._rpc("doctor", 0)           # server unpacks two
"""
    got = _rules(pmix_rpc.run(_tree(tmp_path / "b", {"pmix.py": broken})))
    assert ("arity-mismatch", "doctor") in got
    assert ("dead-rpc", "doctor_ports") in got
