#!/usr/bin/env python
"""XLA cost analysis of the flagship train step: FLOPs and bytes
accessed as the COMPILER counts them, turned into a roofline bound.

step_time >= max(flops / peak_flops, bytes / hbm_bw) — if the measured
step (MFU_SWEEP.jsonl) sits well above both bounds, the gap is
scheduling/fusion, not physics; if the bytes bound dominates, the model
is HBM-bound and the remat/fusion knobs are the lever.

Usage:  python tools/cost_analysis.py [--cpu] [--small]
Appends a JSON line to MFU_SWEEP.jsonl (label "cost-analysis").
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
from bench import _enable_compile_cache  # noqa: E402

_enable_compile_cache()
OUT = os.path.join(REPO, "MFU_SWEEP.jsonl")

HBM_BW = {"v5 lite": 819e9, "v5e": 819e9, "v5p": 2765e9,
          "v4": 1228e9, "v6": 1638e9, "trillium": 1638e9}


def main() -> None:
    t0 = time.time()
    small = "--small" in sys.argv
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 1)
    import jax
    import numpy as np

    from bench import _peak_flops
    from ompi_tpu.models import transformer as tfm
    from ompi_tpu.parallel.mesh import make_mesh

    kind = jax.devices()[0].device_kind
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1},
                     devices=jax.devices()[:1])
    if small:
        cfg = tfm.TransformerConfig(
            vocab=1024, d_model=256, n_heads=4, n_layers=2, d_ff=1024,
            seq=256, attention="xla", ce_chunk=64,
            compute_dtype="bfloat16")
        batch = 2
    else:
        cfg = tfm.TransformerConfig(
            vocab=32_000, d_model=2048, n_heads=16, n_layers=8,
            d_ff=8192, seq=1024, attention="xla", ce_chunk=256,
            compute_dtype="bfloat16")
        batch = 16
    params = tfm.init_params(cfg)
    step, init_opt = tfm.make_train_step(cfg, mesh, lr=1e-3)
    opt_state = init_opt(params)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab,
                        size=(batch, cfg.seq)).astype(np.int32)

    lowered = step.lower(params, opt_state, toks)
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):      # one entry per device program
        ca = ca[0]
    flops = float(ca.get("flops", 0.0))
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    peak = _peak_flops(kind) or 0.0
    bw = next((v for k, v in HBM_BW.items() if k in kind.lower()), 0.0)
    rec = {
        "label": "cost-analysis",
        "backend": kind, "batch": batch, "seq": cfg.seq,
        "xla_flops": flops, "xla_bytes_accessed": bytes_acc,
        "flops_bound_ms": round(flops / peak * 1e3, 2) if peak else None,
        "bytes_bound_ms": round(bytes_acc / bw * 1e3, 2) if bw else None,
        "arith_intensity": round(flops / bytes_acc, 1) if bytes_acc
        else None,
        "wall_s": round(time.time() - t0, 1),
        "ts": time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime()),
    }
    with open(OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec, indent=1))


if __name__ == "__main__":
    main()
