"""Capture an XLA profiler trace of flagship train steps + summarize it.

Closes SURVEY §5's tracing row (the reference pairs per-peer pvar
counters — ompi/mca/common/monitoring/common_monitoring.h:20 — with
external tracers; the TPU-native equivalent is the XLA profiler): wrap
train steps in ``jax.profiler.trace``, keep the TensorBoard-loadable
artifact, and print ONE JSON line summarizing where the step time went —
fraction in MXU-class ops (dot/conv), copies/layout, collectives, and
everything else — which is exactly the breakdown the MFU hunt needs.

Usage:
    python tools/xprof_capture.py                 # live backend, flagship
    python tools/xprof_capture.py --cpu 1 --small # CPU smoke (tests use)

Artifacts: <out>/plugins/profile/<ts>/*.xplane.pb (open in
tensorboard/xprof) and the JSON summary on stdout (also written next to
the trace as summary.json).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Event-name → category. Checked against TPU and CPU xplane naming: TPU op
# events carry HLO op names (fusion.N with the root op leading, dot.N,
# all-reduce.N, copy.N, dynamic-slice...); CPU client lines carry the same
# HLO names plus region markers we skip.
_COLLECTIVE = ("all-reduce", "all-gather", "reduce-scatter", "all-to-all",
               "collective-permute", "collective", "send", "recv",
               "psum", "ppermute")
_MXU = ("dot", "convolution", "einsum", "matmul")
_COPY = ("copy", "transpose", "memset", "bitcast", "reshape", "slice",
         "concatenate", "pad", "broadcast", "gather", "scatter",
         "dynamic-update", "convert")
_SKIP_PREFIX = ("end:", "threadpoollistener", "$", "pjitfunction",
                "xla modules", "steps", "thunkexecutor",
                # control-flow envelopes re-time the ops they contain
                "while", "conditional", "call")


def categorize(name: str) -> str:
    n = name.lower()
    for k in _COLLECTIVE:
        if k in n:
            return "collective"
    for k in _MXU:
        if k in n:
            return "mxu"
    for k in _COPY:
        if k in n:
            return "copy"
    return "other"


def summarize_xplane(pb_path: str) -> dict:
    """Aggregate per-op durations from one .xplane.pb into category
    fractions.  Prefers device planes (/device:TPU:N); falls back to the
    host XLA-client lines (the CPU-backend layout)."""
    import jax.profiler

    pd = jax.profiler.ProfileData.from_file(pb_path)
    per_cat: dict[str, float] = {}
    per_op: dict[str, float] = {}
    n_events = 0

    def eat(line) -> None:
        nonlocal n_events
        # the event list is FLAT: ops executed inside a while/call appear
        # as their own events between the envelope's start and its
        # "end:" marker — skipping the envelope names (in _SKIP_PREFIX)
        # avoids double-counting without losing the inner ops
        for ev in line.events:
            name = ev.name or ""
            low = name.lower()
            if any(low.startswith(p) for p in _SKIP_PREFIX):
                continue
            dur = float(ev.duration_ns or 0.0)
            if dur <= 0:
                continue
            n_events += 1
            cat = categorize(name)
            per_cat[cat] = per_cat.get(cat, 0.0) + dur
            key = name.split(".")[0]
            per_op[key] = per_op.get(key, 0.0) + dur

    device_planes = [p for p in pd.planes
                     if p.name.lower().startswith("/device:")]
    if device_planes:
        for plane in device_planes:
            for line in plane.lines:
                ln = line.name.lower()
                if "module" in ln or ln == "steps":
                    continue  # module envelopes double-count their ops
                eat(line)
    else:
        for plane in pd.planes:
            if plane.name != "/host:CPU":
                continue
            for line in plane.lines:
                if "client" not in line.name.lower():
                    continue  # python-frame lines, not XLA ops
                eat(line)

    total = sum(per_cat.values()) or 1.0
    top = sorted(per_op.items(), key=lambda kv: -kv[1])[:8]
    return {
        "events": n_events,
        "total_op_ms": round(total / 1e6, 3),
        "fractions": {k: round(v / total, 4)
                      for k, v in sorted(per_cat.items(),
                                         key=lambda kv: -kv[1])},
        "top_ops_ms": {k: round(v / 1e6, 3) for k, v in top},
    }


def capture(out_dir: str, steps: int, small: bool) -> dict:
    import jax

    from ompi_tpu.models import transformer as tfm
    from ompi_tpu.parallel.mesh import make_mesh

    kind = getattr(jax.devices()[0], "device_kind", "cpu")
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    # the bench.py flagship config (or its CPU-smoke shrink)
    base = dict(vocab=32_000, d_model=2048, n_heads=16, n_layers=8,
                d_ff=8192, seq=1024, attention="xla", ce_chunk=256)
    batch = 16
    if small:
        base.update(vocab=512, d_model=128, n_heads=8, n_layers=2,
                    d_ff=256, seq=64, ce_chunk=0)
        batch = 2
    cfg = tfm.TransformerConfig(**base, compute_dtype="bfloat16",
                                remat="dots")
    params = tfm.init_params(cfg)
    step, init_opt = tfm.make_train_step(cfg, mesh, lr=1e-3)
    opt_state = init_opt(params)
    tokens = np.random.default_rng(0).integers(
        0, base["vocab"], size=(batch, base["seq"])).astype(np.int32)

    # warm outside the trace so compile time doesn't pollute it
    params, opt_state, loss = step(params, opt_state, tokens)
    jax.block_until_ready(loss)

    os.makedirs(out_dir, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(out_dir):
        for _ in range(steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        jax.block_until_ready(loss)
    wall = time.perf_counter() - t0

    pbs = sorted(glob.glob(os.path.join(out_dir, "**", "*.xplane.pb"),
                           recursive=True), key=os.path.getmtime)
    if not pbs:
        raise RuntimeError(f"no .xplane.pb produced under {out_dir}")
    summary = summarize_xplane(pbs[-1])
    summary.update(
        backend=kind, steps=steps,
        traced_wall_ms=round(wall * 1e3, 1),
        params=int(sum(np.prod(np.shape(p))
                       for p in jax.tree_util.tree_leaves(params))),
        trace=pbs[-1])
    with open(os.path.join(os.path.dirname(pbs[-1]), "summary.json"),
              "w") as f:
        json.dump(summary, f, indent=1)
    return summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(REPO, "xprof_trace"))
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--small", action="store_true",
                    help="tiny model (CPU smoke / tests)")
    ap.add_argument("--cpu", type=int, metavar="N", default=0,
                    help="force an N-device virtual CPU platform")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".jax_cache"))
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", args.cpu)
    summary = capture(args.out, args.steps, args.small)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
