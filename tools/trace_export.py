#!/usr/bin/env python
"""Merge per-rank flight-recorder dumps into one Chrome/Perfetto trace.

Each rank (``OMPI_TPU_TRACE=1`` / ``tpurun --trace``) flushes a
standalone JSON file at finalize/abort:

    ${TMPDIR}/ompi_tpu_trace_<jobid>_rank<r>.json

This tool merges any number of them into a single trace JSON that
chrome://tracing and https://ui.perfetto.dev load directly — one pid per
rank (named ``rank N``), one tid per category (named after the
category), events globally sorted by timestamp.

    python tools/trace_export.py -o merged.json $TMPDIR/ompi_tpu_trace_*_rank*.json
    python tools/trace_export.py -o merged.json --dir $TMPDIR --jobid 7
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_RANK_RE = re.compile(r"ompi_tpu_trace_(\d+)_rank(-?\d+)\.json$")


def dump_glob(jobid: "int | None" = None) -> str:
    """The per-rank dump filename glob (THE place the pattern lives
    beside _RANK_RE — tools/hang_doctor.py's offline mode imports both
    instead of re-hardcoding trace.default_path's format)."""
    return (f"ompi_tpu_trace_{jobid}_rank*.json" if jobid is not None
            else "ompi_tpu_trace_*_rank*.json")

# keep in sync with ompi_tpu.mpi.trace.CATEGORIES (the exporter must not
# import the package: it runs standalone in CI validation steps)
CATEGORIES = ("pml", "btl", "coll", "osc", "io", "ckpt", "datatype",
              "runtime", "errmgr")

#: span names that carry a flow id (``args.fl``) — the send/recv halves
#: of one message; each cross-rank pair becomes a Perfetto flow arrow
FLOW_SEND_SPANS = ("eager_send", "rndv_send")
FLOW_RECV_SPANS = ("eager_recv", "rndv_recv")


def _load(path: str) -> tuple[int, list[dict], dict]:
    """→ (rank, events, otherData) from one per-rank dump."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):          # bare event list: rank from name
        events, other = doc, {}
    else:
        events = doc.get("traceEvents", [])
        other = doc.get("otherData", {}) or {}
    rank = other.get("rank")
    if rank is None:
        m = _RANK_RE.search(os.path.basename(path))
        rank = int(m.group(2)) if m else -1
    if "jobid" not in other:
        m = _RANK_RE.search(os.path.basename(path))
        if m:
            other = dict(other, jobid=int(m.group(1)))
    return int(rank), events, other


def merge(paths: list[str]) -> dict:
    """Merge per-rank dumps into one Chrome trace document."""
    all_events: list[dict] = []
    meta: list[dict] = []
    per_rank: dict[int, dict] = {}
    seen_tids: dict[int, set[int]] = {}
    jobids: set = set()
    for path in paths:
        rank, events, other = _load(path)
        jobids.add(other.get("jobid"))
        if rank in per_rank:
            # two dumps claim the same rank — almost certainly dumps of
            # DIFFERENT jobs in one TMPDIR; their monotonic clocks share
            # no base, so the merged timeline would be fiction
            print(f"trace_export: WARNING: rank {rank} appears in more "
                  f"than one input ({path}); pass --jobid to select one "
                  f"job's dumps", file=sys.stderr)
        per_rank[rank] = {k: other.get(k) for k in
                          ("events_total", "dropped", "counters",
                           "clock_offset_ns",
                           # the collective-recorder tail rides the
                           # merge so one artifact feeds both Perfetto
                           # and the offline hang doctor
                           "collrec", "collrec_total")}
        meta.append({"ph": "M", "name": "process_name", "pid": rank,
                     "tid": 0, "args": {"name": f"rank {rank}"}})
        tids = seen_tids.setdefault(rank, set())
        for ev in events:
            ev = dict(ev)
            ev["pid"] = rank           # one pid per rank, always
            all_events.append(ev)
            tids.add(int(ev.get("tid", 0)))
    if len(jobids - {None}) > 1:
        print(f"trace_export: WARNING: merging dumps from several jobs "
              f"{sorted(j for j in jobids if j is not None)} — their "
              f"timelines are not comparable; pass --jobid",
              file=sys.stderr)
    # event ts are per-machine CLOCK_MONOTONIC; widely differing
    # wall-vs-monotonic anchors mean ranks ran on different hosts (or
    # across reboots) and the merged ordering is fiction
    offs = [v.get("clock_offset_ns") for v in per_rank.values()
            if isinstance(v.get("clock_offset_ns"), (int, float))]
    if offs and max(offs) - min(offs) > 10_000_000_000:   # >10 s skew
        print(f"trace_export: WARNING: monotonic clock bases differ by "
              f"{(max(offs) - min(offs)) / 1e9:.0f}s across dumps "
              f"(different hosts?) — cross-rank event ordering in the "
              f"merged timeline is not meaningful", file=sys.stderr)
    for rank, tids in seen_tids.items():
        for tid in sorted(tids):
            name = CATEGORIES[tid] if tid < len(CATEGORIES) else "other"
            meta.append({"ph": "M", "name": "thread_name", "pid": rank,
                         "tid": tid, "args": {"name": name}})
    all_events.extend(flow_events(all_events))
    all_events.sort(key=lambda e: float(e.get("ts", 0.0)))
    return {
        "displayTimeUnit": "ns",
        "otherData": {"ranks": sorted(per_rank),
                      "per_rank": {str(r): v
                                   for r, v in sorted(per_rank.items())}},
        "traceEvents": meta + all_events,
    }


def flow_events(events: list[dict]) -> list[dict]:
    """Cross-rank flow arrows: every ``{eager,rndv}_send`` span whose
    ``args.fl`` matches an ``{eager,rndv}_recv`` span on another rank
    yields a Perfetto flow pair (``ph s``/``ph f``) — send→recv arrows
    that make inter-rank waits visible in the merged timeline.

    Flow endpoints must land INSIDE their span (Chrome binds a flow
    event to the slice enclosing its ts on that pid/tid), so the start
    rides just before the send span's end and the finish (``bp: "e"``,
    bind-to-enclosing) just before the recv span's end — the arrow runs
    from "payload handed to the wire" to "payload delivered"."""
    sends: dict = {}
    recvs: dict = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        fl = (ev.get("args") or {}).get("fl")
        if fl is None:
            continue
        if ev.get("name") in FLOW_SEND_SPANS:
            sends.setdefault(fl, ev)
        elif ev.get("name") in FLOW_RECV_SPANS:
            recvs.setdefault(fl, ev)
    out: list[dict] = []
    for fl, sev in sends.items():
        rev = recvs.get(fl)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue   # no recv half, or a self-send — no arrow to draw
        s_ts = float(sev["ts"]) + max(0.0, float(sev.get("dur", 0.0)))
        f_ts = float(rev["ts"]) + max(0.0, float(rev.get("dur", 0.0)))
        if f_ts < s_ts:
            # recv span "ends" before the send span: cross-host clock
            # skew (the merge already warns about it).  Both endpoints
            # must land INSIDE their spans to bind, so a clamp can only
            # move f_ts within the recv span — and when even the recv
            # span's end precedes the send endpoint, no binding
            # placement exists: skip the pair rather than draw an arrow
            # anchored to the wrong slice
            continue
        common = {"cat": "flow", "name": "msg", "id": fl}
        out.append({**common, "ph": "s", "ts": s_ts,
                    "pid": sev["pid"], "tid": sev.get("tid", 0)})
        out.append({**common, "ph": "f", "bp": "e", "ts": f_ts,
                    "pid": rev["pid"], "tid": rev.get("tid", 0)})
    return out


def validate(doc: dict) -> list[str]:
    """Chrome-trace shape checks; returns a list of problems (empty =
    valid).  What the CI smoke job runs against the merged artifact."""
    problems = []
    if doc.get("displayTimeUnit") not in ("ms", "ns"):
        problems.append("displayTimeUnit must be 'ms' or 'ns'")
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return problems + ["traceEvents must be a list"]
    last_ts = None
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        # full Chrome phase alphabet: duration, complete, instant,
        # counter, async, flow, sample, object, metadata, memory, mark
        if ph not in ("B", "E", "X", "i", "I", "C", "b", "e", "n",
                      "s", "t", "f", "P", "N", "O", "D", "M", "v", "V",
                      "R"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts not monotonic "
                            f"({ts} < {last_ts})")
        last_ts = ts
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append(f"event {i}: complete span without dur")
        for key in ("name", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key}")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="Merge per-rank ompi_tpu flight-recorder dumps into "
                    "one Chrome/Perfetto trace JSON.")
    p.add_argument("inputs", nargs="*", help="per-rank trace dump files")
    p.add_argument("--dir", default=None,
                   help="scan this directory for ompi_tpu_trace_*.json "
                        "instead of naming files")
    p.add_argument("--jobid", type=int, default=None,
                   help="with --dir: only this job's dumps")
    p.add_argument("-o", "--output", default="ompi_tpu_trace_merged.json")
    p.add_argument("--validate", action="store_true",
                   help="only validate the merged document; nonzero exit "
                        "on schema problems")
    args = p.parse_args(argv)

    paths = list(args.inputs)
    if args.dir:
        paths += sorted(glob.glob(os.path.join(args.dir,
                                               dump_glob(args.jobid))))
    # dedupe (order-preserving): positional inputs may overlap --dir's
    # glob, and a double-loaded rank would double every event
    paths = list(dict.fromkeys(os.path.abspath(p) for p in paths))
    if not paths:
        print("trace_export: no input dumps found", file=sys.stderr)
        return 2

    doc = merge(paths)
    problems = validate(doc)
    if args.validate:
        for pr in problems:
            print(f"trace_export: INVALID: {pr}", file=sys.stderr)
        if problems:
            return 1
        print(f"trace_export: {len(paths)} dump(s) valid "
              f"({len(doc['traceEvents'])} events)")
        return 0
    # merge mode: schema problems are warnings — a post-mortem merge
    # must never throw away a recoverable trace
    for pr in problems:
        print(f"trace_export: WARNING: {pr}", file=sys.stderr)
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_flows = sum(1 for e in doc["traceEvents"] if e.get("ph") == "s")
    cats = sorted({e.get("cat") for e in doc["traceEvents"]
                   if e.get("cat")})
    print(f"trace_export: wrote {args.output} — "
          f"{len(doc['traceEvents'])} events ({n_spans} spans, "
          f"{n_flows} flow arrows) from "
          f"{len(paths)} rank(s); categories: {', '.join(cats)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
