#!/usr/bin/env python
"""Merge per-rank flight-recorder dumps into one Chrome/Perfetto trace.

Each rank (``OMPI_TPU_TRACE=1`` / ``tpurun --trace``) flushes a
standalone JSON file at finalize/abort:

    ${TMPDIR}/ompi_tpu_trace_<jobid>_rank<r>.json

This tool merges any number of them into a single trace JSON that
chrome://tracing and https://ui.perfetto.dev load directly — one pid per
rank (named ``rank N``), one tid per category (named after the
category), events globally sorted by timestamp.

    python tools/trace_export.py -o merged.json $TMPDIR/ompi_tpu_trace_*_rank*.json
    python tools/trace_export.py -o merged.json --dir $TMPDIR --jobid 7
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_RANK_RE = re.compile(r"ompi_tpu_trace_(\d+)_rank(-?\d+)\.json$")


def dump_glob(jobid: "int | None" = None) -> str:
    """The per-rank dump filename glob (THE place the pattern lives
    beside _RANK_RE — tools/hang_doctor.py's offline mode imports both
    instead of re-hardcoding trace.default_path's format)."""
    return (f"ompi_tpu_trace_{jobid}_rank*.json" if jobid is not None
            else "ompi_tpu_trace_*_rank*.json")

# keep in sync with ompi_tpu.mpi.trace.CATEGORIES (the exporter must not
# import the package: it runs standalone in CI validation steps)
CATEGORIES = ("pml", "btl", "coll", "osc", "io", "ckpt", "datatype",
              "runtime", "errmgr")

#: span names that carry a flow id (``args.fl``) — the send/recv halves
#: of one message; each cross-rank pair becomes a Perfetto flow arrow
FLOW_SEND_SPANS = ("eager_send", "rndv_send")
FLOW_RECV_SPANS = ("eager_recv", "rndv_recv")

#: instant names carrying ``args.tc`` — the two ends of one RML envelope
#: (keep in sync with ompi_tpu.runtime.timeline)
RML_SEND_NAME = "rml_send"
RML_RECV_NAME = "rml_recv"


def _load(path: str) -> tuple[int, list[dict], dict]:
    """→ (rank, events, otherData) from one per-rank dump."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):          # bare event list: rank from name
        events, other = doc, {}
    else:
        events = doc.get("traceEvents", [])
        other = doc.get("otherData", {}) or {}
    rank = other.get("rank")
    if rank is None:
        m = _RANK_RE.search(os.path.basename(path))
        rank = int(m.group(2)) if m else -1
    if "jobid" not in other:
        m = _RANK_RE.search(os.path.basename(path))
        if m:
            other = dict(other, jobid=int(m.group(1)))
    return int(rank), events, other


def merge(paths: list[str],
          offsets: "dict[int, float] | None" = None) -> dict:
    """Merge per-rank dumps into one Chrome trace document.

    Clock correction, in preference order:

    - ``offsets`` (``--offsets FILE``): MEASURED per-rank monotonic
      offsets to a common root clock (ns, added to each rank's
      timestamps) — what the clock-sync plane publishes per rank as
      ``rank_clock_to_root_ns`` on the DVM's ``/status``;
    - wall anchors: when no measured offsets are given and the dumps'
      wall-vs-monotonic anchors differ by >10 s (ranks on different
      hosts), every rank is shifted onto the wall axis instead of just
      warning — NTP-grade, but a timeline instead of fiction;
    - none: shared-host dumps (anchors agree) merge raw.

    After correction every send→recv flow pair is checked for
    causality (a recv span ending before its matching send means the
    correction failed); violations land in
    ``otherData.causality_problems`` and are printed as warnings.
    """
    all_events: list[dict] = []
    meta: list[dict] = []
    per_rank: dict[int, dict] = {}
    seen_tids: dict[int, set[int]] = {}
    rank_events: dict[int, list[dict]] = {}
    jobids: set = set()
    for path in paths:
        rank, events, other = _load(path)
        jobids.add(other.get("jobid"))
        if rank in per_rank:
            # two dumps claim the same rank — almost certainly dumps of
            # DIFFERENT jobs in one TMPDIR; their monotonic clocks share
            # no base, so the merged timeline would be fiction
            print(f"trace_export: WARNING: rank {rank} appears in more "
                  f"than one input ({path}); pass --jobid to select one "
                  f"job's dumps", file=sys.stderr)
        per_rank[rank] = {k: other.get(k) for k in
                          ("events_total", "dropped", "counters",
                           "clock_offset_ns",
                           # the collective-recorder tail rides the
                           # merge so one artifact feeds both Perfetto
                           # and the offline hang doctor
                           "collrec", "collrec_total")}
        meta.append({"ph": "M", "name": "process_name", "pid": rank,
                     "tid": 0, "args": {"name": f"rank {rank}"}})
        tids = seen_tids.setdefault(rank, set())
        mine = rank_events.setdefault(rank, [])
        for ev in events:
            ev = dict(ev)
            ev["pid"] = rank           # one pid per rank, always
            all_events.append(ev)
            mine.append(ev)
            tids.add(int(ev.get("tid", 0)))
    if len(jobids - {None}) > 1:
        print(f"trace_export: WARNING: merging dumps from several jobs "
              f"{sorted(j for j in jobids if j is not None)} — their "
              f"timelines are not comparable; pass --jobid",
              file=sys.stderr)
    # event ts are per-machine CLOCK_MONOTONIC; widely differing
    # wall-vs-monotonic anchors mean ranks ran on different hosts (or
    # across reboots) — correct rather than merely warn
    clock_domain = "monotonic_shared"
    anchors = {r: v.get("clock_offset_ns") for r, v in per_rank.items()
               if isinstance(v.get("clock_offset_ns"), (int, float))}
    if offsets:
        clock_domain = "root_monotonic"
        for rank, evs in rank_events.items():
            shift_us = float(offsets.get(rank, 0)) / 1000.0
            per_rank[rank]["applied_offset_ns"] = offsets.get(rank, 0)
            for ev in evs:
                if "ts" in ev:
                    ev["ts"] = float(ev["ts"]) + shift_us
    elif anchors and max(anchors.values()) - min(anchors.values()) \
            > 10_000_000_000:   # >10 s skew: different hosts
        clock_domain = "wall"
        base = min(anchors.values())
        for rank, evs in rank_events.items():
            off = anchors.get(rank)
            if off is None:
                continue   # no anchor: this rank's dump stays raw
            shift_us = float(off - base) / 1000.0
            per_rank[rank]["applied_offset_ns"] = off - base
            for ev in evs:
                if "ts" in ev:
                    ev["ts"] = float(ev["ts"]) + shift_us
    for rank, tids in seen_tids.items():
        for tid in sorted(tids):
            name = CATEGORIES[tid] if tid < len(CATEGORIES) else "other"
            meta.append({"ph": "M", "name": "thread_name", "pid": rank,
                         "tid": tid, "args": {"name": name}})
    problems = causality_problems(all_events)
    for pr in problems:
        print(f"trace_export: WARNING: {pr}", file=sys.stderr)
    all_events.extend(flow_events(all_events))
    if all_events:
        # measured offsets can legally push early events below zero;
        # Perfetto wants a non-negative axis
        base_ts = min(float(e.get("ts", 0.0)) for e in all_events)
        if base_ts < 0:
            for ev in all_events:
                ev["ts"] = float(ev.get("ts", 0.0)) - base_ts
    all_events.sort(key=lambda e: float(e.get("ts", 0.0)))
    return {
        "displayTimeUnit": "ns",
        "otherData": {"ranks": sorted(per_rank),
                      "clock_domain": clock_domain,
                      "causality_problems": problems,
                      "per_rank": {str(r): v
                                   for r, v in sorted(per_rank.items())}},
        "traceEvents": meta + all_events,
    }


def flow_events(events: list[dict]) -> list[dict]:
    """Cross-rank flow arrows (``ph s``/``t``/``f``), three families:

    - p2p: every ``{eager,rndv}_send`` span whose ``(args.tc,
      args.fl)`` matches an ``{eager,rndv}_recv`` span on another rank
      — send→recv arrows that make inter-rank waits visible;
    - collective rounds: every rank's ``coll``-category span of one
      ``(cid, seq)`` chained in completion order;
    - RML envelopes: ``rml_send``/``rml_recv`` instants paired by the
      ``(trace_id, span_id)`` envelope stamp.

    Flow endpoints must land INSIDE their span (Chrome binds a flow
    event to the slice enclosing its ts on that pid/tid), so the start
    rides just before the send span's end and the finish (``bp: "e"``,
    bind-to-enclosing) just before the recv span's end — the arrow runs
    from "payload handed to the wire" to "payload delivered"."""
    sends: dict = {}
    recvs: dict = {}
    colls: dict = {}
    rml_s: dict = {}
    rml_r: dict = {}
    for ev in events:
        args = ev.get("args") or {}
        name = ev.get("name")
        if ev.get("ph") == "X":
            fl = args.get("fl")
            if fl is not None:
                # scoped by the trace id when the header carried one:
                # flow ids from different jobs must not stitch
                key = (args.get("tc"), fl)
                if name in FLOW_SEND_SPANS:
                    sends.setdefault(key, ev)
                elif name in FLOW_RECV_SPANS:
                    recvs.setdefault(key, ev)
            if ev.get("cat") == "coll" and "seq" in args \
                    and "cid" in args:
                colls.setdefault((args["cid"], args["seq"]),
                                 []).append(ev)
        elif name == RML_SEND_NAME and args.get("tc") is not None:
            rml_s.setdefault(tuple(args["tc"]), ev)
        elif name == RML_RECV_NAME and args.get("tc") is not None:
            rml_r.setdefault(tuple(args["tc"]), ev)
    out: list[dict] = []
    for key, sev in sends.items():
        rev = recvs.get(key)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue   # no recv half, or a self-send — no arrow to draw
        # s anchors at the send span's START: the transfer happens
        # somewhere inside the send call, and a fast receiver can
        # legitimately finish unpacking before the sender's span closes
        # (anchoring s at send END would read that as a backward arrow)
        s_ts = float(sev["ts"])
        f_ts = float(rev["ts"]) + max(0.0, float(rev.get("dur", 0.0)))
        if f_ts < s_ts:
            # recv span ends before the send even STARTED: residual
            # clock skew (the merge reports it as a causality problem).
            # Both endpoints must land INSIDE their spans to bind, so
            # no placement exists — skip the pair rather than draw an
            # arrow anchored to the wrong slice
            continue
        tc, fl = key
        fid = f"{tc}:{fl}" if tc is not None else fl
        common = {"cat": "flow", "name": "msg", "id": fid}
        out.append({**common, "ph": "s", "ts": s_ts,
                    "pid": sev["pid"], "tid": sev.get("tid", 0)})
        out.append({**common, "ph": "f", "bp": "e", "ts": f_ts,
                    "pid": rev["pid"], "tid": rev.get("tid", 0)})
    for (cid, seq), group in colls.items():
        # one span per pid (keep the earliest), chained in end order:
        # the arrow path from first-done to last-done rank of one
        # collective round — where the path waits is the straggler
        by_pid: dict = {}
        for ev in group:
            cur = by_pid.get(ev.get("pid"))
            if cur is None or float(ev.get("ts", 0)) < float(
                    cur.get("ts", 0)):
                by_pid[ev.get("pid")] = ev
        chain = sorted(
            by_pid.values(),
            key=lambda e: float(e.get("ts", 0))
            + max(0.0, float(e.get("dur", 0.0))))
        if len(chain) < 2:
            continue   # single-rank round: nothing to stitch
        common = {"cat": "flow", "name": "coll_round",
                  "id": f"coll:{cid}:{seq}"}
        for i, ev in enumerate(chain):
            ph = "s" if i == 0 else ("f" if i == len(chain) - 1 else "t")
            step = {**common, "ph": ph,
                    "ts": float(ev.get("ts", 0))
                    + max(0.0, float(ev.get("dur", 0.0))),
                    "pid": ev["pid"], "tid": ev.get("tid", 0)}
            if ph == "f":
                step["bp"] = "e"
            out.append(step)
    for key, sev in rml_s.items():
        rev = rml_r.get(key)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue
        s_ts, f_ts = float(sev.get("ts", 0)), float(rev.get("ts", 0))
        if f_ts < s_ts:
            continue
        common = {"cat": "flow", "name": "rml",
                  "id": f"rml:{key[0]}:{key[1]}"}
        out.append({**common, "ph": "s", "ts": s_ts,
                    "pid": sev["pid"], "tid": sev.get("tid", 0)})
        out.append({**common, "ph": "f", "bp": "e", "ts": f_ts,
                    "pid": rev["pid"], "tid": rev.get("tid", 0)})
    return out


def causality_problems(events: list[dict]) -> list[str]:
    """Post-correction sanity: a recv span that ENDS before its
    matching send span even STARTED means the applied clock correction
    failed to restore causality (data cannot finish arriving before
    the send call began; comparing span ENDS would false-positive on
    every fast receiver outpacing a slow sender).  One line per
    violated pair; the validator asserts the list empty."""
    sends: dict = {}
    recvs: dict = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        fl = args.get("fl")
        if fl is None:
            continue
        key = (args.get("tc"), fl)
        if ev.get("name") in FLOW_SEND_SPANS:
            sends.setdefault(key, ev)
        elif ev.get("name") in FLOW_RECV_SPANS:
            recvs.setdefault(key, ev)
    problems = []
    for key, sev in sends.items():
        rev = recvs.get(key)
        if rev is None or rev.get("pid") == sev.get("pid"):
            continue
        s_start = float(sev["ts"])
        r_end = float(rev["ts"]) + max(0.0, float(rev.get("dur", 0.0)))
        if r_end < s_start:
            problems.append(
                f"flow {key[1]}: recv on rank {rev.get('pid')} ends "
                f"{s_start - r_end:.1f}us before its send on rank "
                f"{sev.get('pid')} even started — clock correction "
                f"failed to restore causality")
    return problems


def validate(doc: dict) -> list[str]:
    """Chrome-trace shape checks; returns a list of problems (empty =
    valid).  What the CI smoke job runs against the merged artifact."""
    problems = []
    if doc.get("displayTimeUnit") not in ("ms", "ns"):
        problems.append("displayTimeUnit must be 'ms' or 'ns'")
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return problems + ["traceEvents must be a list"]
    last_ts = None
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        # full Chrome phase alphabet: duration, complete, instant,
        # counter, async, flow, sample, object, metadata, memory, mark
        if ph not in ("B", "E", "X", "i", "I", "C", "b", "e", "n",
                      "s", "t", "f", "P", "N", "O", "D", "M", "v", "V",
                      "R"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            problems.append(f"event {i}: ts not monotonic "
                            f"({ts} < {last_ts})")
        last_ts = ts
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            problems.append(f"event {i}: complete span without dur")
        for key in ("name", "pid", "tid"):
            if key not in ev:
                problems.append(f"event {i}: missing {key}")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description="Merge per-rank ompi_tpu flight-recorder dumps into "
                    "one Chrome/Perfetto trace JSON.")
    p.add_argument("inputs", nargs="*", help="per-rank trace dump files")
    p.add_argument("--dir", default=None,
                   help="scan this directory for ompi_tpu_trace_*.json "
                        "instead of naming files")
    p.add_argument("--jobid", type=int, default=None,
                   help="with --dir: only this job's dumps")
    p.add_argument("-o", "--output", default="ompi_tpu_trace_merged.json")
    p.add_argument("--offsets", default=None, metavar="FILE",
                   help="JSON map rank → measured monotonic offset to "
                        "the root clock in ns (the clock-sync plane's "
                        "rank_clock_to_root_ns values); applied to each "
                        "rank's timestamps at merge")
    p.add_argument("--validate", action="store_true",
                   help="only validate the merged document; nonzero exit "
                        "on schema problems")
    p.add_argument("--validate-file", default=None, metavar="FILE",
                   help="validate an EXISTING merged trace JSON (e.g. a "
                        "saved /timeline response) instead of merging; "
                        "nonzero exit on schema or causality problems")
    args = p.parse_args(argv)

    if args.validate_file:
        with open(args.validate_file, encoding="utf-8") as f:
            doc = json.load(f)
        problems = validate(doc)
        problems += causality_problems(doc.get("traceEvents") or [])
        problems += (doc.get("otherData") or {}).get(
            "causality_problems") or []
        for pr in problems:
            print(f"trace_export: INVALID: {pr}", file=sys.stderr)
        if problems:
            return 1
        n = len(doc.get("traceEvents") or [])
        n_flows = sum(1 for e in doc.get("traceEvents") or []
                      if e.get("ph") == "s")
        print(f"trace_export: {args.validate_file} valid "
              f"({n} events, {n_flows} flow arrows)")
        return 0

    paths = list(args.inputs)
    if args.dir:
        paths += sorted(glob.glob(os.path.join(args.dir,
                                               dump_glob(args.jobid))))
    # dedupe (order-preserving): positional inputs may overlap --dir's
    # glob, and a double-loaded rank would double every event
    paths = list(dict.fromkeys(os.path.abspath(p) for p in paths))
    if not paths:
        print("trace_export: no input dumps found", file=sys.stderr)
        return 2

    offsets = None
    if args.offsets:
        with open(args.offsets, encoding="utf-8") as f:
            raw = json.load(f)
        offsets = {int(r): float(v) for r, v in raw.items()
                   if v is not None}

    doc = merge(paths, offsets=offsets)
    problems = validate(doc)
    problems += doc["otherData"].get("causality_problems") or []
    if args.validate:
        for pr in problems:
            print(f"trace_export: INVALID: {pr}", file=sys.stderr)
        if problems:
            return 1
        print(f"trace_export: {len(paths)} dump(s) valid "
              f"({len(doc['traceEvents'])} events)")
        return 0
    # merge mode: schema problems are warnings — a post-mortem merge
    # must never throw away a recoverable trace
    for pr in problems:
        print(f"trace_export: WARNING: {pr}", file=sys.stderr)
    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    n_spans = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
    n_flows = sum(1 for e in doc["traceEvents"] if e.get("ph") == "s")
    cats = sorted({e.get("cat") for e in doc["traceEvents"]
                   if e.get("cat")})
    print(f"trace_export: wrote {args.output} — "
          f"{len(doc['traceEvents'])} events ({n_spans} spans, "
          f"{n_flows} flow arrows) from "
          f"{len(paths)} rank(s); categories: {', '.join(cats)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
