#!/bin/bash
# Watch for the TPU tunnel to come back, then run the MFU sweep once.
# Detached helper for the round-4 build session; state in /tmp/tpuwatch.
mkdir -p /tmp/tpuwatch
cd /root/repo
while true; do
  if timeout 300 python -c "import jax; d=jax.devices(); assert d[0].platform=='tpu', d" \
       >/tmp/tpuwatch/probe.log 2>&1; then
    echo "$(date -u +%FT%TZ) tpu up — starting sweep" >> /tmp/tpuwatch/status
    python tools/mfu_sweep.py >> /tmp/tpuwatch/sweep.log 2>&1
    echo "$(date -u +%FT%TZ) sweep done rc=$?" >> /tmp/tpuwatch/status
    break
  fi
  echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpuwatch/status
  sleep 120
done
