#!/usr/bin/env python
"""Flagship step-time breakdown on the live backend: forward-only vs
forward+backward vs full optimizer step, each as an in-jit chain (same
two-point method as the MFU rows — per-step cost via chained steps, so
the tunnel dispatch round trip amortizes out).

Tells us where the non-MXU time goes: if fwd-only MFU is far above the
train-step MFU, the backward (remat recompute, attention transpose) is
the target; if fwd-only is already low, the forward itself (softmax,
layout, HBM) is.

Appends one JSON line per phase to MFU_SWEEP.jsonl with label
"breakdown-<phase>".
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# children inherit the shared persistent XLA compile cache (the tunnel's
# remote compile helper stalls; a disk hit skips it entirely) — one
# resolution of the cache dir, owned by bench._enable_compile_cache
sys.path.insert(0, REPO)
from bench import _enable_compile_cache  # noqa: E402

_enable_compile_cache()
OUT = os.path.join(REPO, "MFU_SWEEP.jsonl")

CHILD = r"""
import json, sys, time, functools
import numpy as np
phase = sys.argv[1]
t0 = time.time()
import jax
from jax import lax
sys.path.insert(0, {repo!r})
from ompi_tpu.models import transformer as tfm
from ompi_tpu.parallel.mesh import make_mesh
from bench import _peak_flops, _count_params

kind = jax.devices()[0].device_kind
mesh = make_mesh({{"dp": 1, "sp": 1, "tp": 1}}, devices=jax.devices()[:1])
cfg = tfm.TransformerConfig(
    vocab=32_000, d_model=2048, n_heads=16, n_layers=8, d_ff=8192,
    seq=1024, attention="xla", ce_chunk=256, remat="dots",
    compute_dtype="bfloat16")
batch, chain = 16, 32
rng = np.random.default_rng(0)
tokens = jax.device_put(rng.integers(
    0, cfg.vocab, size=(batch, cfg.seq)).astype(np.int32))
params = jax.device_put(tfm.init_params(cfg))
n_params = _count_params(params)
loss_fn = tfm.make_loss_fn(cfg, mesh)

import jax.numpy as jnp


def _perturb(p, carry):
    # Thread the loop carry into the params (one leaf + carry*1e-20):
    # numerically invisible, but a REAL data dependency between scan
    # iterations -- without it XLA hoists the loss computation out of
    # the scan (p and toks are loop-invariant) and the chain times
    # nothing.  (# comments, not a docstring: this code lives inside
    # the CHILD triple-quoted literal.)
    leaves, treedef = jax.tree_util.tree_flatten(p)
    bump = (carry * 1e-20).astype(leaves[0].dtype)
    return jax.tree_util.tree_unflatten(
        treedef, [leaves[0] + bump] + leaves[1:])


if phase == "fwd":
    @jax.jit
    def run(p, toks):
        def body(carry, _):
            loss = loss_fn(_perturb(p, carry), toks)
            return loss, loss
        _, losses = lax.scan(body, jnp.float32(0), None, length=chain)
        return losses
    w = run(params, tokens); _ = float(w[-1])
    t1 = time.perf_counter(); w = run(params, tokens); loss = float(w[-1])
    dt = (time.perf_counter() - t1) / chain
    flop_scale = 1.0 / 3.0        # fwd ≈ 1/3 of the 6N fwd+bwd accounting
elif phase == "grad":
    g_fn = jax.value_and_grad(loss_fn)

    @jax.jit
    def run(p, toks):
        def body(carry, _):
            loss, grads = g_fn(_perturb(p, carry), toks)
            lk = jax.tree_util.tree_leaves(grads)[0]
            return loss + lk[(0,) * lk.ndim].astype(jnp.float32) * 0, loss
        _, losses = lax.scan(body, jnp.float32(0), None, length=chain)
        return losses
    w = run(params, tokens); _ = float(w[-1])
    t1 = time.perf_counter(); w = run(params, tokens); loss = float(w[-1])
    dt = (time.perf_counter() - t1) / chain
    flop_scale = 1.0
else:  # full
    loop, init_opt = tfm.make_train_loop(cfg, mesh, lr=1e-3, steps=chain)
    opt_state = jax.device_put(init_opt(params))
    params, opt_state, losses = loop(params, opt_state, tokens)
    _ = float(losses[-1])
    t1 = time.perf_counter()
    params, opt_state, losses = loop(params, opt_state, tokens)
    loss = float(losses[-1])
    dt = (time.perf_counter() - t1) / chain
    flop_scale = 1.0

n_tokens = tokens.size
fpt = (6 * n_params + 12 * cfg.n_layers * cfg.d_model * cfg.seq) * flop_scale
peak = _peak_flops(kind)
mfu = (fpt * n_tokens / dt / peak) if peak else 0.0
print("RESULT " + json.dumps({{
    "phase": phase, "backend": kind, "mfu_pct": round(mfu * 100, 2),
    "step_ms": round(dt * 1e3, 2), "loss": round(float(loss), 4),
    "params": n_params, "wall_s": round(time.time() - t0, 1),
}}))
""".format(repo=REPO)


def main() -> None:
    for phase in (sys.argv[1:] or ["fwd", "grad", "full"]):
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", CHILD, phase], capture_output=True,
                text=True, timeout=1500, cwd=REPO)
            rec = None
            for line in proc.stdout.splitlines():
                if line.startswith("RESULT "):
                    rec = json.loads(line[len("RESULT "):])
            if rec is None:
                rec = {"error": "no result", "rc": proc.returncode,
                       "stderr_tail": proc.stderr[-700:]}
        except subprocess.TimeoutExpired:
            rec = {"error": "timeout", "wall_s": round(time.time() - t0, 1)}
        rec["label"] = f"breakdown-{phase}"
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"[breakdown] {phase}: {json.dumps(rec)}", flush=True)


if __name__ == "__main__":
    main()
