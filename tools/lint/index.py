"""Project index — parsed ASTs + symbol tables every checker shares.

One parse of the tree, then cheap cross-file passes: modules, classes,
functions (by qualname and by bare method name), imports, and the AST
utilities the checkers lean on (string-literal extraction, f-string →
regex, receiver text, suppression comments).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator, Optional

__all__ = ["ProjectIndex", "ModuleInfo", "ClassInfo", "FunctionInfo",
           "literal_str", "fstring_regex", "call_target_text",
           "iter_calls", "LOCK_FACTORIES"]

#: threading factories whose product counts as a lock for the
#: lock-order analysis (Condition wraps a lock; Event does NOT).
LOCK_FACTORIES = ("Lock", "RLock", "Condition")


class FunctionInfo:
    """One def (module-level function or method)."""

    __slots__ = ("qualname", "module", "path", "node", "cls")

    def __init__(self, qualname: str, module: str, path: str,
                 node: ast.AST, cls: Optional[str]) -> None:
        self.qualname = qualname    # "pkg.mod.Class.meth" / "pkg.mod.func"
        self.module = module        # dotted module name
        self.path = path            # repo-relative file path
        self.node = node            # ast.FunctionDef / AsyncFunctionDef
        self.cls = cls              # "pkg.mod.Class" or None

    def __repr__(self) -> str:
        return f"FunctionInfo({self.qualname})"


class ClassInfo:
    __slots__ = ("qualname", "module", "node", "bases", "methods",
                 "lock_attrs")

    def __init__(self, qualname: str, module: str,
                 node: ast.ClassDef) -> None:
        self.qualname = qualname
        self.module = module
        self.node = node
        self.bases: list[str] = []          # base-class name texts
        self.methods: dict[str, FunctionInfo] = {}
        #: attr name → factory ("Lock"/"RLock"/"Condition") for
        #: ``self.<attr> = threading.Lock()`` style assignments
        self.lock_attrs: dict[str, str] = {}


class ModuleInfo:
    __slots__ = ("name", "path", "tree", "source_lines", "imports",
                 "from_imports", "functions", "classes", "constants")

    def __init__(self, name: str, path: str, tree: ast.Module,
                 source_lines: list[str]) -> None:
        self.name = name
        self.path = path
        self.tree = tree
        self.source_lines = source_lines
        #: local alias → dotted module ("rml" → "ompi_tpu.runtime.rml")
        self.imports: dict[str, str] = {}
        #: local name → (dotted module, original name)
        self.from_imports: dict[str, tuple[str, str]] = {}
        self.functions: dict[str, FunctionInfo] = {}   # bare name → info
        self.classes: dict[str, ClassInfo] = {}        # bare name → info
        #: module-level NAME = "string constant" bindings
        self.constants: dict[str, str] = {}

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""

    def suppressed(self, node: ast.AST, rule: str) -> bool:
        """True when the node's line — or the line just above, for
        statements whose waiver comment won't fit inline — carries an
        explicit ``# lint: <rule>-ok`` waiver.  Several rules may share
        one comment: ``# lint: reader-ok lock-ok``."""
        lineno = getattr(node, "lineno", 0)
        for text in (self.line(lineno), self.line(lineno - 1)):
            if "lint:" in text:
                tokens = text.rsplit("lint:", 1)[1].split()
                if f"{rule}-ok" in tokens:
                    return True
        return False


class ProjectIndex:
    """The parsed tree: every .py under the roots, symbol tables built."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}   # qualname → info
        self.classes: dict[str, ClassInfo] = {}        # qualname → info
        self.methods_by_name: dict[str, list[FunctionInfo]] = {}

    # -- construction ----------------------------------------------------

    @classmethod
    def build(cls, root: str, packages: Optional[list[str]] = None,
              exclude: Optional[list[str]] = None) -> "ProjectIndex":
        """Parse every .py under ``root`` (restricted to ``packages``
        top-level dirs when given), skipping ``exclude`` path prefixes,
        __pycache__, and hidden dirs."""
        idx = cls(root)
        exclude = [os.path.normpath(e) for e in (exclude or [])]
        for path in sorted(cls._walk(root, packages, exclude)):
            idx._add_file(path)
        idx._link()
        return idx

    @staticmethod
    def _walk(root: str, packages: Optional[list[str]],
              exclude: list[str]) -> Iterator[str]:
        tops = packages if packages else [""]
        for top in tops:
            base = os.path.join(root, top) if top else root
            for dirpath, dirnames, filenames in os.walk(base):
                rel = os.path.relpath(dirpath, root)
                dirnames[:] = [
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                    and os.path.normpath(os.path.join(rel, d))
                    not in exclude]
                if os.path.normpath(rel) in exclude:
                    continue
                for fn in filenames:
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)

    def _module_name(self, path: str) -> str:
        rel = os.path.relpath(path, self.root)
        parts = rel[:-3].split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts) if parts else "__root__"

    def _add_file(self, path: str) -> None:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError:
            return  # not this tool's job; py_compile/pytest will say so
        name = self._module_name(path)
        rel = os.path.relpath(path, self.root)
        mod = ModuleInfo(name, rel, tree, src.splitlines())
        self.modules[name] = mod
        self._index_module(mod)

    def _index_module(self, mod: ModuleInfo) -> None:
        for node in mod.tree.body:
            self._index_stmt(mod, node)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(mod, node)

    def _index_stmt(self, mod: ModuleInfo, node: ast.stmt) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = f"{mod.name}.{node.name}"
            fi = FunctionInfo(qn, mod.name, mod.path, node, None)
            mod.functions[node.name] = fi
            self.functions[qn] = fi
        elif isinstance(node, ast.ClassDef):
            cqn = f"{mod.name}.{node.name}"
            ci = ClassInfo(cqn, mod.name, node)
            ci.bases = [ast.unparse(b) for b in node.bases]
            mod.classes[node.name] = ci
            self.classes[cqn] = ci
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    mqn = f"{cqn}.{sub.name}"
                    fi = FunctionInfo(mqn, mod.name, mod.path, sub,
                                      cqn)
                    ci.methods[sub.name] = fi
                    self.functions[mqn] = fi
                    self.methods_by_name.setdefault(sub.name, []).append(fi)
            self._find_lock_attrs(ci)
        elif isinstance(node, ast.Assign):
            # module-level string constants + module-level locks
            val = literal_str(node.value)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and val is not None:
                    mod.constants[tgt.id] = val

    def _find_lock_attrs(self, ci: ClassInfo) -> None:
        """``self.<attr> = threading.Lock()`` (Lock/RLock/Condition)
        anywhere in the class body → a lock attribute."""
        for node in ast.walk(ci.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            fac = _lock_factory_name(node.value.func)
            if fac is None:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    ci.lock_attrs[tgt.attr] = fac

    def _index_import(self, mod: ModuleInfo,
                      node: ast.Import | ast.ImportFrom) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[alias.asname or alias.name.split(".")[0]] = \
                    alias.name
        else:
            src = node.module or ""
            if node.level:  # relative import: resolve against the module
                base = mod.name.split(".")
                # drop the module leaf + (level-1) further packages
                base = base[: max(0, len(base) - node.level)]
                src = ".".join(base + ([src] if src else []))
            for alias in node.names:
                mod.from_imports[alias.asname or alias.name] = \
                    (src, alias.name)

    def _link(self) -> None:
        pass  # reserved for cross-module fixups

    # -- queries ----------------------------------------------------------

    def iter_functions(self) -> Iterator[FunctionInfo]:
        yield from self.functions.values()

    def resolve_module(self, mod: ModuleInfo, alias: str
                       ) -> Optional[ModuleInfo]:
        """A local name used as ``alias.x`` → the project module it
        refers to (via ``import m as alias`` or ``from p import m``)."""
        dotted = mod.imports.get(alias)
        if dotted is None and alias in mod.from_imports:
            src, orig = mod.from_imports[alias]
            dotted = f"{src}.{orig}" if src else orig
        if dotted is None:
            return None
        # exact hit, else try the tail (index roots may strip a prefix)
        if dotted in self.modules:
            return self.modules[dotted]
        for name, m in self.modules.items():
            if name == dotted or name.endswith("." + dotted) \
                    or dotted.endswith("." + name):
                return m
        return None

    def find_module(self, suffix: str) -> Optional[ModuleInfo]:
        """Module by dotted-name suffix ('mpi.trace')."""
        for name, m in self.modules.items():
            if name == suffix or name.endswith("." + suffix):
                return m
        return None

    def find_class(self, name: str) -> Optional[ClassInfo]:
        """Class by bare name, unique across the project."""
        hits = [c for qn, c in self.classes.items()
                if qn.rsplit(".", 1)[-1] == name]
        return hits[0] if len(hits) == 1 else None


def _lock_factory_name(func: ast.expr) -> Optional[str]:
    """'threading.Lock' / bare 'Lock' / 'RLock' / 'Condition' → name."""
    if isinstance(func, ast.Attribute) and func.attr in LOCK_FACTORIES:
        return func.attr
    if isinstance(func, ast.Name) and func.id in LOCK_FACTORIES:
        return func.id
    return None


# ---------------------------------------------------------------------------
# AST utilities
# ---------------------------------------------------------------------------

def literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_regex(node: ast.AST) -> Optional[str]:
    """A JoinedStr (f-string) → anchored regex: literal parts escaped,
    each interpolation a non-greedy wildcard.  None for non-f-strings."""
    if not isinstance(node, ast.JoinedStr):
        return None
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
        else:
            parts.append(".+?")
    return "^" + "".join(parts) + "$"


def call_target_text(call: ast.Call) -> str:
    """The call's func expression as source text ('self.detector.poll')."""
    try:
        return ast.unparse(call.func)
    except Exception:  # noqa: BLE001 — display-only helper
        return "<?>"


def iter_calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def walk_shallow(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` minus subtrees of nested def/lambda — a nested
    function is another stack (thread target / deferred callback), so
    anything inside it must not be attributed to ``node``'s own
    execution."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def iter_calls_shallow(node: ast.AST) -> Iterator[ast.Call]:
    """Calls lexically in ``node``'s own body — subtrees of nested
    def/lambda are pruned.  The call graph uses this: a closure passed
    as a ``threading.Thread`` target runs on ANOTHER stack (the
    spawn-and-return hand-off every reader handler is supposed to use),
    so its calls must not be attributed to the enclosing function."""
    for sub in walk_shallow(node):
        if isinstance(sub, ast.Call):
            yield sub
