"""``python -m tools.lint`` entry point."""

import sys

from tools.lint import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
