"""Call graph + lock-acquisition analysis over the project index.

Name resolution is deliberately conservative (this is a linter, not a
type checker):

- ``self.m(...)`` resolves within the enclosing class, then its
  project-local base classes.
- ``f(...)`` resolves to a same-module function or a ``from x import f``
  target.
- ``mod.f(...)`` resolves through the import table.
- ``obj.m(...)`` (non-self receiver) resolves ONLY when exactly one
  project class defines ``m`` — an ambiguous method name produces no
  edge rather than a speculative one, so reachability findings are
  real paths, not artifacts of name collisions.

Lock analysis: a *lock node* is ``module.Class.attr`` for every
``self.attr = threading.Lock()/RLock()/Condition()`` assignment (or
``module.NAME`` for module-level locks).  Each ``with <lock>:`` block
yields the set of locks acquired *inside* it — directly nested withs
plus everything transitively acquired by calls in the body — producing
a directed acquisition-order graph.  Self-edges are dropped (the graph
has no instance identity: parent→child traversal over two instances of
one class is legitimate nesting, and RLock/Condition re-entry is legal),
cycles between distinct locks are lock-order inversions.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from tools.lint.index import (ClassInfo, FunctionInfo, ModuleInfo,
                              ProjectIndex, iter_calls,
                              iter_calls_shallow)

__all__ = ["CallGraph", "CallSite", "LockAnalysis"]

#: method names that live on ubiquitous stdlib objects (Popen, file,
#: socket, Event, Queue, dict, …): a non-self attribute call with one of
#: these names is far more likely stdlib than the single project method
#: that happens to share it, so unique-name resolution skips them — a
#: speculative edge here turns into a phantom reachability finding
#: (e.g. ``proc.poll()`` on a Popen resolving to ``ShmRingReader.poll``).
_STDLIB_ATTR_DENY = frozenset({
    "poll", "wait", "communicate", "kill", "terminate", "send", "recv",
    "sendall", "accept", "connect", "close", "join", "start", "run",
    "get", "put", "pop", "append", "add", "remove", "discard", "clear",
    "update", "keys", "values", "items", "read", "write", "flush",
    "seek", "tell", "acquire", "release", "notify", "notify_all",
    "set", "is_set", "fileno", "copy", "index", "count", "insert",
    "post",
    "extend", "sort", "split", "strip", "encode", "decode", "lower",
    "upper", "format", "setdefault", "submit", "result", "cancel",
})


class CallSite:
    __slots__ = ("caller", "call", "targets", "receiver")

    def __init__(self, caller: FunctionInfo, call: ast.Call,
                 targets: list[FunctionInfo], receiver: str) -> None:
        self.caller = caller
        self.call = call
        self.targets = targets      # resolved project callees ([] if none)
        self.receiver = receiver    # receiver source text ("" for bare f())


class CallGraph:
    @classmethod
    def of(cls, index: ProjectIndex) -> "CallGraph":
        """The index's call graph, built once — reader-thread and
        lock-order both need it, and the build (every call site in the
        tree resolved) dominates lint wall-clock if repeated."""
        graph = getattr(index, "_callgraph", None)
        if graph is None:
            graph = cls(index)
            index._callgraph = graph  # type: ignore[attr-defined]
        return graph

    def __init__(self, index: ProjectIndex) -> None:
        self.index = index
        #: caller qualname → ordered call sites
        self.sites: dict[str, list[CallSite]] = {}
        #: caller qualname → set of callee qualnames
        self.edges: dict[str, set[str]] = {}
        self._reach_memo: dict[str, set[str]] = {}
        for fi in index.iter_functions():
            self._build_function(fi)

    # -- construction ----------------------------------------------------

    def _build_function(self, fi: FunctionInfo) -> None:
        mod = self.index.modules[fi.module]
        sites: list[CallSite] = []
        edges: set[str] = set()
        # shallow walk: a nested def is another stack (thread target /
        # deferred callback) — its calls are not this function's calls
        for call in iter_calls_shallow(fi.node):
            targets, recv = self._resolve(mod, fi, call)
            sites.append(CallSite(fi, call, targets, recv))
            edges.update(t.qualname for t in targets)
        self.sites[fi.qualname] = sites
        self.edges[fi.qualname] = edges

    def _resolve(self, mod: ModuleInfo, fi: FunctionInfo,
                 call: ast.Call) -> tuple[list[FunctionInfo], str]:
        func = call.func
        if isinstance(func, ast.Name):
            t = self._resolve_bare(mod, func.id)
            return ([t] if t else []), ""
        if not isinstance(func, ast.Attribute):
            return [], ""
        recv = func.value
        recv_text = _safe_unparse(recv)
        meth = func.attr
        # self.m() → enclosing class, then project-local bases
        if isinstance(recv, ast.Name) and recv.id == "self" and fi.cls:
            t = self._resolve_method(self.index.classes[fi.cls], meth)
            if t is not None:
                return [t], recv_text
            return self._resolve_unique(meth), recv_text
        # mod.f() → import table
        if isinstance(recv, ast.Name):
            target_mod = self.index.resolve_module(mod, recv.id)
            if target_mod is not None:
                if meth in target_mod.functions:
                    return [target_mod.functions[meth]], recv_text
                if meth in target_mod.classes:   # Mod.Class(...) ctor
                    ctor = target_mod.classes[meth].methods.get("__init__")
                    return ([ctor] if ctor else []), recv_text
                return [], recv_text
        # obj.m() → unique project method name only
        return self._resolve_unique(meth), recv_text

    def _resolve_bare(self, mod: ModuleInfo, name: str
                      ) -> Optional[FunctionInfo]:
        if name in mod.functions:
            return mod.functions[name]
        if name in mod.classes:   # local Class(...) ctor
            return mod.classes[name].methods.get("__init__")
        if name in mod.from_imports:
            src, orig = mod.from_imports[name]
            target = self.index.find_module(src) if src else None
            if target is not None:
                if orig in target.functions:
                    return target.functions[orig]
                if orig in target.classes:
                    return target.classes[orig].methods.get("__init__")
        return None

    def _resolve_method(self, ci: ClassInfo, meth: str
                        ) -> Optional[FunctionInfo]:
        if meth in ci.methods:
            return ci.methods[meth]
        for base in ci.bases:
            bci = self.index.find_class(base.rsplit(".", 1)[-1])
            if bci is not None and bci.qualname != ci.qualname:
                t = self._resolve_method(bci, meth)
                if t is not None:
                    return t
        return None

    def _resolve_unique(self, meth: str) -> list[FunctionInfo]:
        if meth in _STDLIB_ATTR_DENY:
            return []
        cands = self.index.methods_by_name.get(meth, [])
        return [cands[0]] if len(cands) == 1 else []

    def edges_excluding(self, rule: str) -> dict[str, set[str]]:
        """Call-graph edges, minus call sites waived with an explicit
        ``# lint: <rule>-ok`` comment — the per-edge escape hatch for
        contracts the analysis cannot see (e.g. a callee that only
        blocks when a flag argument says so)."""
        out: dict[str, set[str]] = {}
        for qn, sites in self.sites.items():
            fi = self.index.functions[qn]
            mod = self.index.modules[fi.module]
            tgts = out.setdefault(qn, set())
            for cs in sites:
                if cs.targets and mod.suppressed(cs.call, rule):
                    continue
                tgts.update(t.qualname for t in cs.targets)
        return out

    # -- reachability -----------------------------------------------------

    def reachable(self, start: str) -> set[str]:
        """All qualnames reachable from ``start`` (inclusive)."""
        memo = self._reach_memo.get(start)
        if memo is not None:
            return memo
        seen: set[str] = set()
        stack = [start]
        while stack:
            qn = stack.pop()
            if qn in seen:
                continue
            seen.add(qn)
            stack.extend(self.edges.get(qn, ()))
        self._reach_memo[start] = seen
        return seen

    def shortest_path(self, start: str, goal_set: set[str]
                      ) -> Optional[list[str]]:
        """BFS path start → any member of goal_set (for messages)."""
        from collections import deque

        prev: dict[str, Optional[str]] = {start: None}
        q = deque([start])
        while q:
            qn = q.popleft()
            if qn in goal_set:
                path = [qn]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])  # type: ignore[arg-type]
                return list(reversed(path))
            for nxt in sorted(self.edges.get(qn, ())):
                if nxt not in prev:
                    prev[nxt] = qn
                    q.append(nxt)
        return None


# ---------------------------------------------------------------------------
# lock analysis
# ---------------------------------------------------------------------------

class LockAnalysis:
    """Direct + transitive lock acquisitions per function, and the
    acquisition-order edges between distinct lock nodes."""

    def __init__(self, graph: CallGraph,
                 modules: Optional[set[str]] = None) -> None:
        self.graph = graph
        self.index = graph.index
        self.modules = modules    # restrict analysis to these modules
        #: edges minus `# lint: lock-ok`-waived call sites
        self.edges = graph.edges_excluding("lock")
        #: qualname → [(lock_id, kind, With-node)]
        self.direct: dict[str, list[tuple[str, str, ast.With]]] = {}
        self._trans: Optional[dict[str, frozenset[str]]] = None
        for fi in self.index.iter_functions():
            if modules is not None and fi.module not in modules:
                continue
            self.direct[fi.qualname] = list(self._direct_locks(fi))

    def _direct_locks(self, fi: FunctionInfo
                      ) -> Iterator[tuple[str, str, ast.With]]:
        # same nested-def pruning as the call graph: a closure's locks
        # are acquired on the closure's (usually another thread's) stack
        stack = list(ast.iter_child_nodes(fi.node))
        nodes: list[ast.AST] = []
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            nodes.append(sub)
            stack.extend(ast.iter_child_nodes(sub))
        for node in nodes:
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                got = self._lock_id(fi, item.context_expr)
                if got is not None:
                    yield got[0], got[1], node

    def _lock_id(self, fi: FunctionInfo, expr: ast.expr
                 ) -> Optional[tuple[str, str]]:
        """``with self._lock`` / ``with _module_lock`` → (id, kind)."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            attr = expr.attr
            if expr.value.id == "self" and fi.cls:
                ci = self.index.classes[fi.cls]
                kind = self._class_lock(ci, attr)
                if kind is not None:
                    return f"{fi.cls}.{attr}", kind
                return None
            # obj.lock: unique lock-attr name across project classes
            owners = [(ci, k) for ci in self.index.classes.values()
                      for a, k in ci.lock_attrs.items() if a == attr]
            if len(owners) == 1:
                ci, kind = owners[0]
                return f"{ci.qualname}.{attr}", kind
            return None
        if isinstance(expr, ast.Name):
            mod = self.index.modules[fi.module]
            # module-level lock: NAME = threading.Lock() at top level
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    from tools.lint.index import _lock_factory_name

                    fac = _lock_factory_name(node.value.func)
                    if fac is None:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) \
                                and tgt.id == expr.id:
                            return f"{mod.name}.{expr.id}", fac
        return None

    def _class_lock(self, ci: ClassInfo, attr: str) -> Optional[str]:
        if attr in ci.lock_attrs:
            return ci.lock_attrs[attr]
        for base in ci.bases:
            bci = self.index.find_class(base.rsplit(".", 1)[-1])
            if bci is not None and bci.qualname != ci.qualname:
                k = self._class_lock(bci, attr)
                if k is not None:
                    return k
        return None

    def transitive(self, qualname: str) -> frozenset[str]:
        """Locks ``qualname`` may acquire, directly or via any callee.

        Computed as one global fixpoint rather than a memoized DFS: a
        lazy DFS with a cycle guard permanently memoizes an INCOMPLETE
        set for every non-root member of a call cycle (mutually
        recursive helpers), silently hiding their locks from cycle
        detection and the reader-shared set."""
        if self._trans is None:
            locks: dict[str, set[str]] = {
                qn: {lid for lid, _k, _n in d}
                for qn, d in self.direct.items()}
            changed = True
            while changed:
                changed = False
                for qn, callees in self.edges.items():
                    cur = locks.setdefault(qn, set())
                    n = len(cur)
                    for c in callees:
                        got = locks.get(c)
                        if got:
                            cur |= got
                    if len(cur) != n:
                        changed = True
            self._trans = {qn: frozenset(s) for qn, s in locks.items()}
        return self._trans.get(qualname, frozenset())

    def held_call_sites(self, fi: FunctionInfo
                        ) -> Iterator[tuple[str, CallSite]]:
        """(held_lock_id, call site) for every call lexically inside a
        with-lock block of ``fi``."""
        sites = self.graph.sites.get(fi.qualname, [])
        for lid, _kind, wnode in self.direct.get(fi.qualname, ()):
            body_calls = {id(c) for stmt in wnode.body
                          for c in iter_calls_shallow(stmt)}
            for site in sites:
                if id(site.call) in body_calls:
                    yield lid, site


def _safe_unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display-only
        return "<?>"
