"""ompi-lint — project-invariant static analysis for the ompi_tpu tree.

The stack spans five concurrency planes (PML reader threads, gossip
beats, daemon heartbeats, arena waits, launcher reapers) and several
cross-file name registries (MCA config vars, pvar counters, rml tags,
FT frame ops, PMIx RPCs).  Most of the bugs review keeps catching are
*mechanically checkable*: an RPC issued from a reader thread, a frame
``op`` with no dispatch branch, a config var read that was never
registered, a lock taken under another lock in the opposite order.
This package is the tooling that checks them, so protocol invariants
are enforced by CI instead of reviewer stamina (the same discipline the
reference's memchecker/valgrind integration carries for opal).

Two checker families:

- **Registry/protocol exhaustiveness** (cross-file symbol-table
  passes): ``var-registry``, ``pvar-spec``, ``rml-tag``, ``frame-op``,
  ``pmix-rpc``.
- **Thread-context safety** (call-graph reachability):
  ``reader-thread`` (blocking calls on transport reader paths),
  ``lock-order`` (lock-acquisition cycles + RPC/sleep under lock).

Run ``python -m tools.lint`` from the repo root.  Each checker owns an
exit-code bit (see ``tools.lint.checkers.ALL``); the driver exits with
the OR of every failing checker, so CI logs show *which* invariant
broke.  Findings can be grandfathered into ``tools/lint/baseline.json``
(see ``--write-baseline``); the baseline is meant to stay empty or
carry a justification per entry.

Suppression: a finding on a line ending in ``# lint: <rule>-ok`` is
intentional and skipped (e.g. ``# lint: reader-ok`` on a call a reader
thread is explicitly allowed to make).
"""

from __future__ import annotations

__all__ = ["main"]


def main(argv: list[str] | None = None) -> int:
    from tools.lint.driver import run

    return run(argv)
