"""The ompi-lint driver — build the index once, run every checker,
apply the baseline, exit with the OR of failing checkers' bits.

Usage::

    python -m tools.lint                      # full tree, all checkers
    python -m tools.lint --checker frame-op --checker pmix-rpc
    python -m tools.lint --root tests/fixtures/lint/bad_frame_op
    python -m tools.lint --write-baseline     # grandfather current findings
    python -m tools.lint --list               # checker catalogue + bits

The mypy gate (``--strict`` over the typed core surface, see
``STRICT_SURFACE``) runs when mypy is importable and is skipped with a
note otherwise — the container this repo grows in has no mypy, CI
installs it.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from tools.lint import checkers
from tools.lint.baseline import DEFAULT_PATH, Baseline
from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex

#: packages indexed on a full-tree run (repo-root relative)
DEFAULT_PACKAGES = ["ompi_tpu", "tools"]
#: never index: the linter itself (its fixtures are deliberately bad)
DEFAULT_EXCLUDE = ["tools/lint"]

#: the mypy --strict surface: the checker-indexed core the lint package
#: itself leans on (config-var registry, MCA selection, pvar specs)
STRICT_SURFACE = [
    "ompi_tpu/core/config.py",
    "ompi_tpu/core/mca.py",
    "ompi_tpu/mpi/mpit.py",
    "ompi_tpu/mpi/trace.py",
]


def run(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="project-invariant static analysis for ompi_tpu")
    ap.add_argument("--root", default=None,
                    help="tree root to lint (default: repo root; "
                    "point at a fixture tree to lint it instead)")
    ap.add_argument("--checker", action="append", dest="only",
                    metavar="NAME", help="run only these checkers "
                    "(repeatable; default: all)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {DEFAULT_PATH})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--no-mypy", action="store_true",
                    help="skip the mypy --strict gate")
    ap.add_argument("--list", action="store_true",
                    help="list checkers + exit-code bits and exit")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.write_baseline and args.root and not args.baseline:
        # a fixture-tree run must not overwrite the repo's baseline
        ap.error("--write-baseline with --root needs an explicit "
                 "--baseline (refusing to overwrite the repo default)")

    if args.list:
        for name, (bit, fn) in sorted(checkers.ALL.items(),
                                      key=lambda kv: kv[1][0]):
            doc = (sys.modules[fn.__module__].__doc__ or "").strip()
            head = doc.splitlines()[0] if doc else ""
            print(f"  {name:<14} bit {bit:<3} {head}")
        print(f"  {'mypy-strict':<14} bit {checkers.MYPY_BIT:<3} "
              f"mypy --strict over {len(STRICT_SURFACE)} core modules")
        return 0

    repo_root = args.root or _repo_root()
    full_tree = args.root is None
    index = ProjectIndex.build(
        repo_root,
        packages=DEFAULT_PACKAGES if full_tree else None,
        exclude=DEFAULT_EXCLUDE if full_tree else None)

    selected = args.only or sorted(checkers.ALL)
    unknown = [n for n in selected if n not in checkers.ALL]
    if unknown:
        ap.error(f"unknown checker(s): {unknown}; see --list")

    all_findings: list[Finding] = []
    per_checker: dict[str, list[Finding]] = {}
    for name in selected:
        _bit, fn = checkers.ALL[name]
        got = fn(index)
        per_checker[name] = got
        all_findings += got

    if args.write_baseline:
        path = args.baseline or DEFAULT_PATH
        # merge-write: existing justifications survive, and a --checker
        # subset run cannot delete other checkers' entries
        Baseline.write(path, all_findings,
                       keep=Baseline.load(path).entries,
                       ran=set(selected))
        print(f"wrote {len(all_findings)} finding(s) to {path}")
        return 0

    # a --root (fixture-tree) run must not read the REPO's baseline
    # either: its entries could grandfather identical fingerprints in
    # the fixture and its justified entries would all read as stale
    if args.no_baseline or (args.root and not args.baseline):
        baseline = Baseline({})
    else:
        baseline = Baseline.load(args.baseline)

    exit_code = 0
    total_new = total_old = 0
    for name in selected:
        bit, _fn = checkers.ALL[name]
        new, old, _stale = baseline.split(per_checker[name])
        total_new += len(new)
        total_old += len(old)
        for f in new:
            print(f.render())
        if not args.quiet:
            for f in old:
                print(f"(grandfathered) {f.render()}")
        if new:
            exit_code |= bit

    # staleness is a property of the WHOLE run: an entry is stale only
    # when no checker produced it — so it is checked globally, and only
    # when every checker ran (a --checker subset would false-flag the
    # other checkers' grandfathered entries)
    if not args.only:
        _new, _old, stale = baseline.split(all_findings)
        all_bits = 0
        for _name, (bit, _fn) in checkers.ALL.items():
            all_bits |= bit
        for fp in stale:
            owner = fp.split(":", 1)[0]
            print(f"stale baseline entry {fp!r}: no current finding "
                  f"matches — remove it with the fix")
            if owner in checkers.ALL:
                exit_code |= checkers.ALL[owner][0]
            else:
                # renamed/typo'd checker prefix: attributing it to any
                # one family would lie — raise every bit and let the
                # printed fingerprint do the naming
                exit_code |= all_bits

    mypy_note = ""
    # the mypy gate belongs to FULL runs only, like the stale check — a
    # --checker subset must not fail on a family it did not select
    if not args.no_mypy and full_tree and not args.only:
        ok, mypy_note = _run_mypy(repo_root)
        if not ok:
            exit_code |= checkers.MYPY_BIT

    if not args.quiet or exit_code:
        n_ck = len(selected)
        print(f"ompi-lint: {n_ck} checker(s), {total_new} new finding(s)"
              f", {total_old} grandfathered"
              + (f"; {mypy_note}" if mypy_note else ""))
    return exit_code


def _repo_root() -> str:
    # tools/lint/driver.py → repo root is two dirs up from tools/
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _run_mypy(repo_root: str) -> tuple[bool, str]:
    """mypy --strict over STRICT_SURFACE.  Skipped (ok=True) when mypy
    is not installed — the dev container has none; CI installs it."""
    try:
        import mypy  # noqa: F401
    except ImportError:
        return True, "mypy not installed — strict gate skipped"
    cfg = os.path.join(repo_root, "tools", "lint", "mypy.ini")
    cmd = [sys.executable, "-m", "mypy", "--config-file", cfg,
           *STRICT_SURFACE]
    proc = subprocess.run(cmd, cwd=repo_root, capture_output=True,
                          text=True)
    if proc.returncode != 0:
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return False, f"mypy --strict FAILED over {len(STRICT_SURFACE)} modules"
    return True, f"mypy --strict clean over {len(STRICT_SURFACE)} modules"
