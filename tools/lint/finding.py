"""Finding — one reported invariant violation.

A finding's ``fingerprint`` is deliberately line-number-free: baselines
must survive unrelated edits above the finding, so the identity is
(checker, rule, symbol) — the *what*, not the *where*.  The location is
carried separately for display.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str          # e.g. "var-registry"
    rule: str             # e.g. "unregistered-read"
    symbol: str           # the offending name (var/tag/op/rpc/lock path)
    message: str          # human-readable one-liner
    path: str = ""        # repo-relative file
    line: int = 0

    @property
    def fingerprint(self) -> str:
        return f"{self.checker}:{self.rule}:{self.symbol}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}: " if self.path else ""
        return f"{loc}[{self.checker}/{self.rule}] {self.message}"
