"""lock-order — acquisition-order cycles and blocking work under locks.

Builds the lock-acquisition graph across ``mpi/`` + ``runtime/`` (the
two packages whose locks nest across module boundaries): a directed
edge A→B for every path that acquires B while holding A — directly
nested ``with`` blocks plus everything transitively acquired by calls
made inside a ``with A`` body.  Checks:

- ``cycle``: a cycle between *distinct* locks (AB/BA inversion — the
  deadlock needs two threads, which is exactly why review keeps
  missing it).  Self-edges are not reported: the graph has no instance
  identity (parent→child traversal over two instances of one class is
  legitimate ordered nesting) and RLock/Condition re-entry is legal.
- ``rpc-under-lock``: a blocking PMIx RPC reachable with a lock held —
  the lock is held across a server round-trip, so every other thread
  needing it stalls on the network.
- ``sleep-under-lock``: ``time.sleep`` with a lock held (backoff loops
  belong outside the critical section; ``Condition.wait`` releases and
  is fine).

The blocking-under-lock rules apply only to *reader-shared* locks —
locks some transport reader path also acquires.  A lock that exists to
serialize an intentionally-blocking operation against its own kind
(``Window._origin_lock`` "serializes blocking ops", the once-per-
process ``runtime._lock`` held across the init modex) is that design,
not a finding; a reader-shared lock held across a sleep or an RPC
stalls the frame pipeline, which is the bug class this hunts.

Waive an intentional edge with ``# lint: lock-ok`` on the call line.
"""

from __future__ import annotations

import ast

from tools.lint.callgraph import CallGraph, LockAnalysis
from tools.lint.checkers.reader_thread import (_augment_with_sinks,
                                               _entry_points,
                                               _reachable, _short,
                                               _shortest)
from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex, walk_shallow

CHECKER = "lock-order"

#: packages whose locks participate (dotted-name fragments); fixture
#: trees are small enough that everything participates
_SCOPE_FRAGMENTS = ("mpi", "runtime", "core")


def run(index: ProjectIndex) -> list[Finding]:
    scoped = {name for name in index.modules
              if any(f".{frag}." in f".{name}."
                     for frag in _SCOPE_FRAGMENTS)} or set(index.modules)
    graph = CallGraph.of(index)
    locks = LockAnalysis(graph, modules=scoped)
    findings: list[Finding] = []
    findings += _check_cycles(index, graph, locks)
    findings += _check_blocking_under_lock(index, graph, locks)
    return findings


# -- acquisition-order cycles ----------------------------------------------

def _check_cycles(index: ProjectIndex, graph: CallGraph,
                  locks: LockAnalysis) -> list[Finding]:
    #: lock A → {lock B: (example function, line)}
    edges: dict[str, dict[str, tuple[str, int]]] = {}
    for qn, acquired in locks.direct.items():
        fi = index.functions[qn]
        mod = index.modules[fi.module]
        for lid, _kind, wnode in acquired:
            inner: set[str] = set()
            # directly nested with-locks (shallow: a closure's withs
            # run on the closure's stack — same pruning as the call
            # graph, or the approved spawn-and-return hand-off would
            # fabricate an acquisition edge that cannot deadlock)
            for sub in walk_shallow(wnode):
                if not isinstance(sub, ast.With):
                    continue
                for item in sub.items:
                    got = locks._lock_id(fi, item.context_expr)
                    if got is not None:
                        inner.add(got[0])
            # locks acquired by calls made while held
            for held, site in locks.held_call_sites(fi):
                if held != lid:
                    continue
                if mod.suppressed(site.call, "lock"):
                    continue
                for t in site.targets:
                    inner |= locks.transitive(t.qualname)
            for b in inner:
                if b != lid:
                    edges.setdefault(lid, {}).setdefault(
                        b, (qn, wnode.lineno))

    findings = []
    for cycle in _find_cycles(edges):
        ordered = sorted(cycle)
        sym = "->".join(_short(x) for x in ordered)
        # any edge inside the SCC serves as the example location (the
        # sorted order is canonical, not a walkable path)
        ex_fn, ex_line = next(
            edges[a][b] for a in ordered for b in edges.get(a, {})
            if b in cycle and b != a)
        fi = index.functions[ex_fn]
        findings.append(Finding(
            CHECKER, "cycle", sym,
            f"lock-order inversion among {{{', '.join(ordered)}}} "
            f"(one edge via {ex_fn})",
            fi.path, ex_line))
    return findings


def _find_cycles(edges: dict[str, dict[str, tuple[str, int]]]
                 ) -> list[list[str]]:
    """Distinct elementary cycles via SCC decomposition (one finding
    per strongly connected component with ≥2 locks)."""
    # Tarjan
    adj = {a: sorted(bs) for a, bs in edges.items()}
    idx: dict[str, int] = {}
    low: dict[str, int] = {}
    stack: list[str] = []
    on: set[str] = set()
    out: list[list[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        idx[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in adj.get(v, ()):
            if w not in idx:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], idx[w])
        if low[v] == idx[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                out.append(sorted(comp))

    all_nodes = set(adj) | {b for bs in adj.values() for b in bs}
    for v in sorted(all_nodes):
        if v not in idx:
            strong(v)
    return out


# -- blocking work under a held lock ---------------------------------------

def _check_blocking_under_lock(index: ProjectIndex, graph: CallGraph,
                               locks: LockAnalysis) -> list[Finding]:
    edges, sink_sites = _augment_with_sinks(index, graph, rule="lock")
    reader_locks = _reader_shared_locks(graph, locks,
                                        _entry_points(index, graph))
    findings = []
    reported: set[str] = set()
    for qn in sorted(locks.direct):
        fi = index.functions[qn]
        mod = index.modules[fi.module]
        for held, site in locks.held_call_sites(fi):
            if held not in reader_locks:
                continue
            if mod.suppressed(site.call, "lock"):
                continue
            sinks_here: dict[str, list[str]] = {}
            # the call itself may be a sink edge of qn at this site…
            for sink in ("<sink:rpc>", "<sink:sleep>"):
                if (mod.path, site.call.lineno) in \
                        sink_sites.get((qn, sink), ()):
                    sinks_here[sink] = [qn]
            # …or reachable through the callee
            for t in site.targets:
                reach = _reachable(edges, t.qualname)
                for sink in reach & {"<sink:rpc>", "<sink:sleep>"}:
                    path = _shortest(edges, t.qualname, sink) or []
                    sinks_here.setdefault(sink, [qn] + path[:-1])
            for sink, chain in sorted(sinks_here.items()):
                rule = ("rpc-under-lock" if sink == "<sink:rpc>"
                        else "sleep-under-lock")
                what = ("a blocking PMIx RPC" if sink == "<sink:rpc>"
                        else "time.sleep")
                sym = f"{_short(held)}@{_short(qn)}"
                if f"{rule}:{sym}" in reported:
                    continue
                reported.add(f"{rule}:{sym}")
                via = " -> ".join(_short(q) for q in chain)
                findings.append(Finding(
                    CHECKER, rule, sym,
                    f"{what} reachable while holding {held} "
                    f"(via {via})", mod.path, site.call.lineno))
    return findings


def _reader_shared_locks(graph: CallGraph, locks: LockAnalysis,
                         entries: set[str]) -> set[str]:
    """Locks acquired anywhere on a reader-thread path (directly or via
    calls) — the set for which blocking-while-held stalls the frame
    pipeline."""
    shared: set[str] = set()
    for entry in entries:
        shared |= locks.transitive(entry)
    return shared
