"""Checker registry.

Each checker is ``run(index) -> list[Finding]`` plus a stable exit-code
bit.  The driver ORs the bits of every checker that produced
non-grandfathered findings, so a CI log's exit status names the broken
invariant family.
"""

from __future__ import annotations

from typing import Callable

from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex

from tools.lint.checkers import (frame_op, lock_order, pmix_rpc,
                                 pvar_spec, reader_thread, rml_tag,
                                 span_pairing, var_registry)

#: name → (exit-code bit, run function)
ALL: dict[str, tuple[int, Callable[[ProjectIndex], list[Finding]]]] = {
    "var-registry": (1, var_registry.run),
    "pvar-spec": (2, pvar_spec.run),
    "rml-tag": (4, rml_tag.run),
    "frame-op": (8, frame_op.run),
    "pmix-rpc": (16, pmix_rpc.run),
    "reader-thread": (32, reader_thread.run),
    "lock-order": (64, lock_order.run),
    "span-pairing": (256, span_pairing.run),
}

#: the mypy gate owns the remaining bit (see tools.lint.driver)
MYPY_BIT = 128
