"""pmix-rpc — client RPC names exist on the server, with enough args.

The PMIx wire protocol is stringly-typed: ``PMIxClient._rpc("cmd", …)``
frames a tuple, ``PMIxServer._handle`` switches on the literal.  An
unknown cmd raises server-side ("unknown command") and surfaces as a
PMIxError at every caller; a branch unpacking more args than a client
sends is a per-call ValueError (the PR-7 ``report_failed``
legacy-probe class).  Checks:

- ``unknown-rpc``: a client ``_rpc("x", …)`` with no ``cmd == "x"``
  branch in ``_handle``.
- ``arity-mismatch``: a client call passing fewer args than the
  branch's *unconditional* accesses require (fixed tuple-unpacks,
  unguarded ``args[i]`` subscripts, ``args[:k]`` slices; accesses
  under a ``len(args)`` guard are optional by construction).
- ``dead-rpc``: a ``_handle`` branch no client call ever names.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex, iter_calls, literal_str

CHECKER = "pmix-rpc"


def run(index: ProjectIndex) -> list[Finding]:
    handle = _find_handler(index)
    if handle is None:
        return []
    branches, handle_path = handle

    calls: dict[str, list[tuple[int, str, int]]] = {}
    for mod in index.modules.values():
        for call in iter_calls(mod.tree):
            f = call.func
            if not (isinstance(f, ast.Attribute) and f.attr == "_rpc"
                    and call.args):
                continue
            cmd = literal_str(call.args[0])
            if cmd is None:
                continue
            argc = len(call.args) - 1
            if any(isinstance(a, ast.Starred) for a in call.args):
                argc = -1   # variadic forward: arity unknowable
            calls.setdefault(cmd, []).append(
                (argc, mod.path, call.lineno))

    findings: list[Finding] = []
    for cmd, sites in sorted(calls.items()):
        if cmd not in branches:
            for _argc, path, line in sites:
                findings.append(Finding(
                    CHECKER, "unknown-rpc", cmd,
                    f"client sends RPC {cmd!r} but the server _handle "
                    f"has no branch for it", path, line))
            continue
        required, _line = branches[cmd]
        for argc, path, line in sites:
            if argc >= 0 and argc < required:
                findings.append(Finding(
                    CHECKER, "arity-mismatch", cmd,
                    f"RPC {cmd!r} sent with {argc} arg(s) but the "
                    f"server branch unconditionally reads {required}",
                    path, line))
    for cmd, (_req, line) in sorted(branches.items()):
        if cmd not in calls:
            findings.append(Finding(
                CHECKER, "dead-rpc", cmd,
                f"server _handle has a branch for {cmd!r} but no "
                f"client ever sends it", handle_path, line))
    return findings


def _find_handler(index: ProjectIndex
                  ) -> Optional[tuple[dict[str, tuple[int, int]], str]]:
    """The ``_handle(self, cmd, args)`` dispatcher →
    {cmd literal: (required arity, line)}."""
    for fi in index.iter_functions():
        if fi.qualname.rsplit(".", 1)[-1] != "_handle" or fi.cls is None:
            continue
        args = fi.node.args.args
        names = [a.arg for a in args]
        if names[-2:] != ["cmd", "args"]:
            continue
        branches: dict[str, tuple[int, int]] = {}
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.If)
                    and isinstance(node.test, ast.Compare)):
                continue
            t = node.test
            if not (isinstance(t.left, ast.Name) and t.left.id == "cmd"
                    and len(t.ops) == 1
                    and isinstance(t.ops[0], ast.Eq)):
                continue
            cmd = literal_str(t.comparators[0])
            if cmd is None:
                continue
            req = max((_required_arity(stmt) for stmt in node.body),
                      default=0)
            branches[cmd] = (req, node.lineno)
        return branches, index.modules[fi.module].path
    return None


def _required_arity(node: ast.AST, guarded: bool = False) -> int:
    """Max index of ``args`` this subtree unconditionally needs —
    accesses under a ``len(args)`` guard (``if``/conditional
    expression) count as optional."""
    if isinstance(node, ast.If):
        g = guarded or _mentions_len_args(node.test)
        req = _required_arity(node.test, guarded)
        for sub in node.body + node.orelse:
            req = max(req, _required_arity(sub, g))
        return req
    if isinstance(node, ast.IfExp):
        g = guarded or _mentions_len_args(node.test)
        return max(_required_arity(node.test, guarded),
                   _required_arity(node.body, g),
                   _required_arity(node.orelse, g))
    req = 0
    # tuple-unpack of args: a, b, c = args (optional under a guard,
    # same as subscripts — the legacy-fallback pattern unpacks inside
    # an `if len(args) >= n:` arm)
    if not guarded and isinstance(node, ast.Assign) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "args":
        for tgt in node.targets:
            if isinstance(tgt, ast.Tuple):
                req = max(req, len(tgt.elts))
    if (not guarded and isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Name)
            and node.value.id == "args"):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
            req = max(req, sl.value + 1)
        elif isinstance(sl, ast.Slice) \
                and isinstance(sl.upper, ast.Constant) \
                and isinstance(sl.upper.value, int) \
                and sl.lower is None:
            req = max(req, sl.upper.value)
    for child in ast.iter_child_nodes(node):
        req = max(req, _required_arity(child, guarded))
    return req


def _mentions_len_args(test: ast.expr) -> bool:
    for sub in ast.walk(test):
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "len" and sub.args
                and isinstance(sub.args[0], ast.Name)
                and sub.args[0].id == "args"):
            return True
    return False
