"""frame-op — every emitted frame type/op literal has a dispatch branch.

Two wire planes ride header dicts through the BTLs:

- the PML data/control plane: ``{"t": "<type>", …}`` frames dispatched
  by ``_on_frame``'s if-chain;
- the FT/gossip control plane: ``{"t": "ft", "op": "<op>", …}`` frames
  dispatched by ``on_ft_frame``.

Both dispatchers end in ``_log.error("unknown …")`` — so an emitted
literal with no branch is a frame that silently vanishes at every
receiver (the PR-7 class of bug: a new gossip op added on the send
side only).  Checks:

- ``unhandled-op``: an emitted ``op``/``t`` literal with no comparison
  branch in the matching dispatcher.
- ``unemitted-branch``: a dispatcher branch for a literal nothing in
  the tree emits (dead protocol arm).
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex, literal_str

CHECKER = "frame-op"

#: (plane, dispatch function name, header key, emit-filter, assumed)
#: emit-filter: a dict literal participates when f(keys) is true;
#: ``assumed`` supplies the keys a non-dict-literal emission form
#: (``hdr["op"] = …`` / ``hdr.update(op=…)``) cannot carry — the "op"
#: key only exists on t="ft" frames, so those forms are ft emissions
_PLANES = (
    ("ft", "on_ft_frame", "op",
     lambda keys: keys.get("t") == "ft", {"t": "ft"}),
    ("pml", "_on_frame", "t",
     lambda keys: True, {}),
)


def run(index: ProjectIndex) -> list[Finding]:
    findings: list[Finding] = []
    for plane, dispatch_name, key, emit_ok, assumed in _PLANES:
        emitted = _collect_emitted(index, key, emit_ok, assumed)
        dispatched = _collect_dispatched(index, dispatch_name, key)
        if dispatched is None:
            continue   # no dispatcher in this tree — plane not present
        branch_lits, disp_path, disp_line = dispatched
        for lit, (path, line) in sorted(emitted.items()):
            if lit not in branch_lits:
                findings.append(Finding(
                    CHECKER, "unhandled-op", f"{plane}:{lit}",
                    f"frame {key}={lit!r} is emitted but "
                    f"{dispatch_name} has no branch for it — the frame "
                    f"is dropped at every receiver", path, line))
        for lit in sorted(branch_lits - set(emitted)):
            findings.append(Finding(
                CHECKER, "unemitted-branch", f"{plane}:{lit}",
                f"{dispatch_name} dispatches {key}={lit!r} but nothing "
                f"in the tree emits it (dead protocol arm)",
                disp_path, disp_line))
    return findings


# -- emit side -------------------------------------------------------------

def _collect_emitted(index: ProjectIndex, key: str, emit_ok,
                     assumed: dict) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}

    def emit(lit: Optional[str], keys: dict, mod, node) -> None:
        if lit is not None and emit_ok(keys) \
                and not mod.suppressed(node, "frame"):
            out.setdefault(lit, (mod.path, node.lineno))

    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Dict):
                keys: dict[str, Optional[str]] = {}
                vals: dict[str, ast.expr] = {}
                for k, v in zip(node.keys, node.values):
                    kl = literal_str(k) if k is not None else None
                    if kl is not None:
                        keys[kl] = literal_str(v)
                        vals[kl] = v
                if key in keys:
                    for lit in _value_literals(vals[key]):
                        emit(lit, {**keys, key: lit}, mod, node)
            elif isinstance(node, ast.Assign):
                # hdr["t"] = "eager" style emission
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Subscript)
                            and literal_str(tgt.slice) == key):
                        for lit in _value_literals(node.value):
                            emit(lit, {**assumed, key: lit}, mod, node)
            elif isinstance(node, ast.Call):
                # hdr.update(t="rndv", …) style emission
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr == "update":
                    for kw in node.keywords:
                        if kw.arg == key:
                            for lit in _value_literals(kw.value):
                                emit(lit, {**assumed, key: lit},
                                     mod, node)
    return out


def _value_literals(node: ast.expr) -> list[str]:
    """The literal(s) an emitted value can take — plain constants plus
    both arms of a conditional (``"rndv" if big else "eager"``)."""
    lit = literal_str(node)
    if lit is not None:
        return [lit]
    if isinstance(node, ast.IfExp):
        return _value_literals(node.body) + _value_literals(node.orelse)
    return []


# -- dispatch side ---------------------------------------------------------

def _collect_dispatched(index: ProjectIndex, dispatch_name: str,
                        key: str
                        ) -> Optional[tuple[set[str], str, int]]:
    for fi in index.iter_functions():
        if fi.qualname.rsplit(".", 1)[-1] != dispatch_name:
            continue
        mod = index.modules[fi.module]
        lits = _branch_literals(fi.node, key)
        return lits, mod.path, fi.node.lineno
    return None


def _branch_literals(func: ast.AST, key: str) -> set[str]:
    """String literals the dispatcher compares the header key against:
    tracks ``x = hdr[key]`` / ``x = hdr.get(key)`` bindings, then
    collects literals from ``x == "lit"`` / ``x != "lit"`` /
    ``x in ("a", "b")`` comparisons (and the direct
    ``hdr.get(key) == "lit"`` form)."""
    tracked: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Assign) and _reads_key(node.value, key,
                                                      set()):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    tracked.add(tgt.id)
    lits: set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Compare):
            continue
        operands = [node.left, *node.comparators]
        if not any(_reads_key(op, key, tracked) for op in operands):
            continue
        for op in operands:
            lit = literal_str(op)
            if lit is not None:
                lits.add(lit)
            elif isinstance(op, (ast.Tuple, ast.List, ast.Set)):
                for el in op.elts:
                    el_lit = literal_str(el)
                    if el_lit is not None:
                        lits.add(el_lit)
    return lits


def _reads_key(node: ast.expr, key: str, tracked: set[str]) -> bool:
    if isinstance(node, ast.Name):
        return node.id in tracked
    if isinstance(node, ast.Subscript):
        return literal_str(node.slice) == key
    if isinstance(node, ast.Call):
        f = node.func
        return (isinstance(f, ast.Attribute) and f.attr == "get"
                and bool(node.args)
                and literal_str(node.args[0]) == key)
    return False
