"""reader-thread — no blocking calls on transport reader paths.

The contract every transport layer repeats ("BTL reader thread: never
block, sends only via the worker queue") is exactly the reference's
event-loop-callback discipline — and PR 7 fixed the same violation
twice (an adoption notice RPC'd straight from ``peer_reincarnated`` on
a reader thread).  This checker makes the rule mechanical: classify
every function reachable from a reader-thread entry point and flag

- ``rpc-on-reader``: a blocking PMIx RPC (``PMIxClient._rpc`` or any
  client method that transitively calls it),
- ``sleep-on-reader``: ``time.sleep``,
- ``subprocess-on-reader``: any ``subprocess.*`` call,
- ``park-on-reader``: a native GIL-released park
  (``_native/arena.c``'s ``ompi_tpu_arena_wait*`` /
  ``ompi_tpu_ring_wait_any`` via ctypes) — the APPROVED blocking form
  for a read/poll loop's own idle window (those entries are exempt,
  any depth: parking is the loop's job), but still a block that must
  not ride a frame-dispatch path (``_on_frame``/``on_ft_frame``/rml
  callbacks), where it would stall every peer behind one wait

on those paths.  Entry points are (a) the configured transport read
loops below and (b) every callback registered via ``register_recv``
(rml handlers run on the link reader thread, per the RmlNode module
doc).

A call a reader path is *allowed* to make (hand-off wrappers, spawn-
and-return helpers) is waived with ``# lint: reader-ok`` on the call
line; paths through a thread-spawn boundary (``threading.Thread``
targets are separate stacks) are not followed because the Thread
constructor only stores the callable — the call graph never links
through it.
"""

from __future__ import annotations

import ast

from tools.lint.callgraph import CallGraph
from tools.lint.finding import Finding
from tools.lint.index import FunctionInfo, ProjectIndex, iter_calls

CHECKER = "reader-thread"

#: qualname suffixes of the transport read loops (entry points beyond
#: the auto-collected register_recv callbacks).  Fixture trees provide
#: their own read loops under the same names.
ENTRY_SUFFIXES = (
    "._read_loop",        # RmlNode / TcpBTL link readers
    "._accept_loop",      # listener threads (same no-block contract)
    "._poll_loop",        # btl_shm ring poller
    "._on_frame",         # PML frame dispatch (called by BTL readers)
    ".on_ft_frame",       # FT control dispatch (same thread)
)

_SINK_RULES = {
    "<sink:rpc>": ("rpc-on-reader",
                   "a blocking PMIx RPC"),
    "<sink:sleep>": ("sleep-on-reader",
                     "time.sleep"),
    "<sink:subprocess>": ("subprocess-on-reader",
                          "a subprocess call"),
    "<sink:native-park>": ("park-on-reader",
                           "a native GIL-released park"),
}

#: the ctypes entry points of _native/arena.c that BLOCK (bounded
#: slices, but blocks nonetheless) — recognized as sinks wherever the
#: library handle is called through an attribute
NATIVE_PARK_ATTRS = frozenset({
    "ompi_tpu_arena_wait", "ompi_tpu_arena_wait_all",
    "ompi_tpu_arena_wait_change", "ompi_tpu_ring_wait_any",
    # btl/tcp native plane: bounded GIL-released network parks
    "ompi_tpu_net_poll", "ompi_tpu_net_recv_into", "ompi_tpu_net_writev",
    "ompi_tpu_net_send3",
})


def run(index: ProjectIndex) -> list[Finding]:
    graph = CallGraph.of(index)
    edges, sink_sites = _augment_with_sinks(index, graph)
    entries = _entry_points(index, graph)

    findings: list[Finding] = []
    reported: set[tuple[str, str]] = set()
    for entry in sorted(entries):
        reach = _reachable(edges, entry)
        for sink, (rule, what) in sorted(_SINK_RULES.items()):
            if sink not in reach:
                continue
            path = _shortest(edges, entry, sink)
            via = path[-2] if path and len(path) >= 2 else entry
            if (sink == "<sink:sleep>" and via == entry
                    and entry.rsplit(".", 1)[-1].endswith("_loop")):
                continue   # a read/poll loop's own idle pacing sleep
            if (sink == "<sink:native-park>"
                    and entry.rsplit(".", 1)[-1].endswith("_loop")):
                # the GIL-released park IS the approved idle form for a
                # poll/read loop (any depth: the park helper is one hop)
                continue
            key = (rule, f"{entry}->{via}")
            if key in reported:
                continue
            reported.add(key)
            site = (sink_sites.get((via, sink)) or [("", 0)])[0]
            chain = " -> ".join(_short(q) for q in (path or [entry]))
            findings.append(Finding(
                CHECKER, rule, f"{_short(entry)}->{_short(via)}",
                f"reader-thread entry {_short(entry)} reaches {what}: "
                f"{chain}", site[0], site[1]))
    return findings


# -- entry points ----------------------------------------------------------

#: attribute hooks invoked from link reader threads (RmlNode calls
#: ``on_peer_lost`` straight from ``_read_loop``; ProcBTL calls
#: ``on_fast`` — the PML's compiled fast-lane dispatch — from its
#: reader) — an assignment ``x.on_peer_lost = self._cb`` makes
#: ``_cb`` a reader entry
HOOK_ATTRS = ("on_peer_lost", "on_fast", "on_frame", "on_ctrl")


def _entry_points(index: ProjectIndex, graph: CallGraph) -> set[str]:
    entries: set[str] = set()

    def add(target: FunctionInfo | None) -> None:
        if target is not None:
            tmod = index.modules[target.module]
            if not tmod.suppressed(target.node, "reader"):
                entries.add(target.qualname)

    for fi in index.iter_functions():
        qn = fi.qualname
        if any(qn.endswith(sfx) for sfx in ENTRY_SUFFIXES):
            mod = index.modules[fi.module]
            if not mod.suppressed(fi.node, "reader"):
                entries.add(qn)
    for fi in index.iter_functions():
        mod = index.modules[fi.module]
        # register_recv callbacks run on the rml link reader thread —
        # including what a lambda wrapper calls (`lambda o, p:
        # self._on_x(...)` is the common adapter form)
        for call in iter_calls(fi.node):
            f = call.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr == "register_recv"
                    and len(call.args) >= 2):
                continue
            cb = call.args[1]
            if isinstance(cb, ast.Lambda):
                for inner in iter_calls(cb.body):
                    targets, _recv = graph._resolve(mod, fi, inner)
                    for t in targets:
                        add(t)
                continue
            add(_resolve_callback(graph, fi, cb))
        # reader-thread hook attributes (x.on_peer_lost = self._cb)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and tgt.attr in HOOK_ATTRS:
                    add(_resolve_callback(graph, fi, node.value))
    return entries


def _resolve_callback(graph: CallGraph, caller: FunctionInfo,
                      cb: ast.expr) -> FunctionInfo | None:
    if isinstance(cb, ast.Attribute) and isinstance(cb.value, ast.Name) \
            and cb.value.id == "self" and caller.cls:
        ci = graph.index.classes[caller.cls]
        return graph._resolve_method(ci, cb.attr)
    if isinstance(cb, ast.Name):
        mod = graph.index.modules[caller.module]
        return graph._resolve_bare(mod, cb.id)
    return None


# -- sinks -----------------------------------------------------------------

def _augment_with_sinks(index: ProjectIndex, graph: CallGraph,
                        rule: str = "reader"
                        ) -> tuple[dict[str, set[str]],
                                   dict[tuple[str, str],
                                        list[tuple[str, int]]]]:
    """A copy of the call-graph edges with pseudo sink nodes wired in,
    minus ``# lint: <rule>-ok``-waived call sites.
    Returns (edges, (caller, sink) → EVERY call-site location) — all
    sites, because a consumer may need to know whether one specific
    call (e.g. the one under a lock) is the sink, not merely that the
    function contains one somewhere."""
    rpc_methods = _rpc_method_names(index, graph)
    edges = graph.edges_excluding(rule)
    sites: dict[tuple[str, str], list[tuple[str, int]]] = {}

    for qn, call_sites in graph.sites.items():
        fi = graph.index.functions[qn]
        mod = index.modules[fi.module]
        for cs in call_sites:
            f = cs.call.func
            sink = None
            if isinstance(f, ast.Attribute):
                recv = cs.receiver.lower()
                if f.attr == "sleep" and recv.endswith("time"):
                    sink = "<sink:sleep>"
                elif recv.split(".")[-1] == "subprocess" \
                        or (f.attr == "Popen"
                            and "subprocess" in recv):
                    sink = "<sink:subprocess>"
                elif f.attr in rpc_methods and _rpc_receiver(
                        recv, f.attr):
                    sink = "<sink:rpc>"
                elif f.attr == "_rpc" and cs.targets \
                        and any(t.qualname.endswith("._rpc")
                                for t in cs.targets):
                    sink = "<sink:rpc>"
                elif f.attr in NATIVE_PARK_ATTRS:
                    sink = "<sink:native-park>"
            elif isinstance(f, ast.Name):
                # bare-imported forms: `from time import sleep`,
                # `from subprocess import run/Popen/check_call…`
                src = str(mod.from_imports.get(f.id, ("", ""))[0])
                orig = str(mod.from_imports.get(f.id, ("", f.id))[1])
                if src == "time" and orig == "sleep":
                    sink = "<sink:sleep>"
                elif src == "subprocess":
                    sink = "<sink:subprocess>"
            if sink is None:
                continue
            if mod.suppressed(cs.call, rule):
                continue
            edges.setdefault(qn, set()).add(sink)
            sites.setdefault((qn, sink), []).append(
                (mod.path, cs.call.lineno))
    return edges, sites


#: rpc method names that also live on dicts/queues/etc. — for these the
#: receiver must literally BE the client, not merely mention one
#: (``self._client_epoch.get(...)`` is a dict read, not an RPC)
_GENERIC_RPC_NAMES = frozenset(
    {"get", "put", "abort", "fence", "barrier", "finalize", "set"})


def _rpc_receiver(recv: str, attr: str) -> bool:
    """Does the receiver text plausibly denote the PMIx client?"""
    last = recv.split(".")[-1]
    if attr in _GENERIC_RPC_NAMES:
        return last in ("client", "_client") or last.endswith("pmix")
    return "client" in recv or "pmix" in recv


def _rpc_method_names(index: ProjectIndex, graph: CallGraph
                      ) -> set[str]:
    """Method names of the client class (the one defining ``_rpc``)
    that transitively reach ``_rpc`` — each is a blocking RPC."""
    names: set[str] = set()
    for ci in index.classes.values():
        if "_rpc" not in ci.methods:
            continue
        rpc_qn = ci.methods["_rpc"].qualname
        for mname, mfi in ci.methods.items():
            if rpc_qn in graph.reachable(mfi.qualname):
                names.add(mname)
    names.discard("__init__")   # construction is a connect, not a call
    return names


# -- graph helpers ---------------------------------------------------------

def _reachable(edges: dict[str, set[str]], start: str) -> set[str]:
    seen: set[str] = set()
    stack = [start]
    while stack:
        qn = stack.pop()
        if qn in seen:
            continue
        seen.add(qn)
        stack.extend(edges.get(qn, ()))
    return seen


def _shortest(edges: dict[str, set[str]], start: str,
              goal: str) -> list[str] | None:
    from collections import deque

    prev: dict[str, str | None] = {start: None}
    q = deque([start])
    while q:
        qn = q.popleft()
        if qn == goal:
            path = [qn]
            while prev[path[-1]] is not None:
                path.append(prev[path[-1]])  # type: ignore[arg-type]
            return list(reversed(path))
        for nxt in sorted(edges.get(qn, ())):
            if nxt not in prev:
                prev[nxt] = qn
                q.append(nxt)
    return None


def _short(qualname: str) -> str:
    parts = qualname.split(".")
    return ".".join(parts[-2:]) if len(parts) > 1 else qualname
