"""pvar-spec — always-on counters/histograms and their catalogue tuples
(`_COUNTER_SPECS` / `_HIST_SPECS`) agree in both directions.

``trace.count(name)`` does ``counters[name] += 1`` and
``trace.record_hist(name, …)`` opens ``hists`` series validated against
``_HIST_SPECS`` — an undeclared name is a KeyError on a hot path, and a
spec nobody records is a dead pvar that exports a forever-zero metric
and rots the catalogue.  Checks:

- ``undeclared-counter``: a ``count("x")`` bump (or ``counters["x"]``
  access) naming no ``_COUNTER_SPECS`` entry.  F-string names must
  match ≥1 spec.
- ``dead-pvar``: a ``_COUNTER_SPECS`` entry never bumped anywhere.
- ``undeclared-hist``: a ``record_hist("x", …)`` naming no
  ``_HIST_SPECS`` entry (f-string names expanded like counters).
- ``dead-hist``: a ``_HIST_SPECS`` entry with no recording site.
- ``unknown-agg-metric``: an ``AGG_METRICS`` entry (the per-job
  aggregated-metric family the DVM scrape endpoint sums across ranks
  as ``ompi_tpu_job_*``) naming no ``_COUNTER_SPECS`` counter — a
  renamed counter would otherwise silently vanish from the scrape
  surface while the aggregate kept exporting a forever-zero sum.
- ``unknown-agg-hist``: the same cross-check for ``AGG_HISTS`` (the
  per-job element-wise histogram sums) against ``_HIST_SPECS``.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tools.lint.finding import Finding
from tools.lint.index import (ProjectIndex, fstring_regex, iter_calls,
                              literal_str)

CHECKER = "pvar-spec"


def run(index: ProjectIndex) -> list[Finding]:
    findings: list[Finding] = []
    specs = collect_specs(index, "_COUNTER_SPECS")
    if specs is not None:
        findings += _check_family(
            index, specs, arg_fn=_count_arg,
            undeclared_kind="undeclared-counter",
            dead_kind="dead-pvar",
            spec_tuple="_COUNTER_SPECS", verb="bumped",
            record_verb="count() call", subscript_store="counters")
        for name, path, line in collect_agg_names(index, "AGG_METRICS"):
            if name not in specs[0]:
                findings.append(Finding(
                    CHECKER, "unknown-agg-metric", name,
                    f"AGG_METRICS entry {name!r} names no "
                    f"_COUNTER_SPECS counter — the per-job "
                    f"ompi_tpu_job_ sum on the scrape endpoint would "
                    f"export forever-zero (renamed counter?)",
                    path, line))
    hspecs = collect_specs(index, "_HIST_SPECS")
    if hspecs is not None:
        findings += _check_family(
            index, hspecs, arg_fn=_record_hist_arg,
            undeclared_kind="undeclared-hist",
            dead_kind="dead-hist",
            spec_tuple="_HIST_SPECS", verb="recorded",
            record_verb="record_hist() call", subscript_store="hists")
        for name, path, line in collect_agg_names(index, "AGG_HISTS"):
            if name not in hspecs[0]:
                findings.append(Finding(
                    CHECKER, "unknown-agg-hist", name,
                    f"AGG_HISTS entry {name!r} names no _HIST_SPECS "
                    f"histogram — the per-job ompi_tpu_job_ bucket sum "
                    f"on the scrape endpoint would export forever-zero "
                    f"(renamed histogram?)", path, line))
    return findings


def _check_family(index: ProjectIndex,
                  specs: tuple[set[str], str, dict[str, int]],
                  arg_fn, undeclared_kind: str, dead_kind: str,
                  spec_tuple: str, verb: str, record_verb: str,
                  subscript_store: str) -> list[Finding]:
    """The both-directions discipline for one spec catalogue: every
    recording site names a declared spec (literal or f-string), every
    declared spec has a recording site."""
    spec_names, spec_mod, spec_line = specs
    findings: list[Finding] = []
    used: set[str] = set()

    for mod in index.modules.values():
        for call in iter_calls(mod.tree):
            arg = arg_fn(mod, call)
            if arg is None:
                continue
            lit = literal_str(arg)
            if lit is not None:
                if lit in spec_names:
                    used.add(lit)
                elif not mod.suppressed(call, "pvar"):
                    findings.append(Finding(
                        CHECKER, undeclared_kind, lit,
                        f"{lit!r} {verb} but not declared in "
                        f"{spec_tuple}", mod.path, call.lineno))
                continue
            rx = fstring_regex(arg)
            if rx is not None:
                hits = {n for n in spec_names if re.match(rx, n)}
                if hits:
                    used |= hits
                elif not mod.suppressed(call, "pvar"):
                    findings.append(Finding(
                        CHECKER, undeclared_kind, rx,
                        f"dynamic name {rx!r} matches no {spec_tuple} "
                        f"entry", mod.path, call.lineno))
        # counters["x"] / hists["x"] subscripts also keep a spec alive
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Subscript) \
                    and _is_store(node.value, subscript_store):
                lit = literal_str(node.slice)
                if lit is not None and lit in spec_names:
                    used.add(lit)

    for name in sorted(set(spec_names) - used):
        findings.append(Finding(
            CHECKER, dead_kind, name,
            f"{spec_tuple} entry {name!r} is never {verb} by any "
            f"{record_verb}", spec_mod, spec_line.get(name, 0)))
    return findings


def collect_specs(index: ProjectIndex, tuple_name: str = "_COUNTER_SPECS"
                  ) -> Optional[tuple[set[str], str, dict[str, int]]]:
    """A spec catalogue tuple (``_COUNTER_SPECS`` / ``_HIST_SPECS``) →
    (names, path, name→line)."""
    for mod in index.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == tuple_name
                            for t in node.targets)):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            names: set[str] = set()
            lines: dict[str, int] = {}
            for el in node.value.elts:
                if isinstance(el, (ast.Tuple, ast.List)) and el.elts:
                    nm = literal_str(el.elts[0])
                    if nm is not None:
                        names.add(nm)
                        lines[nm] = el.lineno
            return names, mod.path, lines
    return None


def collect_agg_metrics(index: ProjectIndex
                        ) -> list[tuple[str, str, int]]:
    """Back-compat alias: the ``AGG_METRICS`` entries."""
    return collect_agg_names(index, "AGG_METRICS")


def collect_agg_names(index: ProjectIndex, tuple_name: str
                      ) -> list[tuple[str, str, int]]:
    """Every ``AGG_METRICS``/``AGG_HISTS`` tuple's string entries →
    [(name, path, line)] — the aggregated name families the DVM
    scrape endpoint exports per job."""
    out: list[tuple[str, str, int]] = []
    for mod in index.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == tuple_name
                            for t in node.targets)):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            for el in node.value.elts:
                nm = literal_str(el)
                if nm is not None:
                    out.append((nm, mod.path, el.lineno))
    return out


def _count_arg(mod, call: ast.Call) -> Optional[ast.expr]:
    """The name argument of a counter bump: ``trace.count(x)`` /
    ``trace_mod.count(x)`` / bare ``count(x)`` imported from the trace
    module.  Plain ``<anything-else>.count(x)`` (str/list methods) is
    not a bump."""
    f = call.func
    if not call.args:
        return None
    if isinstance(f, ast.Attribute) and f.attr == "count":
        recv = f.value
        if isinstance(recv, ast.Name) and "trace" in recv.id:
            return call.args[0]
        return None
    if isinstance(f, ast.Name) and f.id == "count":
        src = mod.from_imports.get("count")
        if src is not None and "trace" in src[0]:
            return call.args[0]
        if "count" in mod.functions:   # the trace module itself
            return call.args[0]
    return None


def _record_hist_arg(mod, call: ast.Call) -> Optional[ast.expr]:
    """The name argument of a histogram record: ``trace.record_hist(x,
    …)`` / bare ``record_hist(x, …)`` imported from the trace module."""
    f = call.func
    if not call.args:
        return None
    if isinstance(f, ast.Attribute) and f.attr == "record_hist":
        recv = f.value
        if isinstance(recv, ast.Name) and "trace" in recv.id:
            return call.args[0]
        return None
    if isinstance(f, ast.Name) and f.id == "record_hist":
        src = mod.from_imports.get("record_hist")
        if src is not None and "trace" in src[0]:
            return call.args[0]
        if "record_hist" in mod.functions:   # the trace module itself
            return call.args[0]
    return None


def _is_store(node: ast.expr, store: str) -> bool:
    if isinstance(node, ast.Name):
        return node.id == store
    if isinstance(node, ast.Attribute):
        return node.attr == store
    return False
