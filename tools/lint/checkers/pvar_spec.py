"""pvar-spec — always-on counters and their `_COUNTER_SPECS` catalogue
agree in both directions.

``trace.count(name)`` does ``counters[name] += 1`` — an undeclared name
is a KeyError on a hot path (the counters dict is seeded from
``_COUNTER_SPECS`` only), and a spec nobody bumps is a dead pvar that
exports a forever-zero metric and rots the catalogue.  Checks:

- ``undeclared-counter``: a ``count("x")`` bump (or ``counters["x"]``
  access) naming no ``_COUNTER_SPECS`` entry.  F-string names must
  match ≥1 spec.
- ``dead-pvar``: a ``_COUNTER_SPECS`` entry never bumped anywhere.
- ``unknown-agg-metric``: an ``AGG_METRICS`` entry (the per-job
  aggregated-metric family the DVM scrape endpoint sums across ranks
  as ``ompi_tpu_job_*``) naming no ``_COUNTER_SPECS`` counter — a
  renamed counter would otherwise silently vanish from the scrape
  surface while the aggregate kept exporting a forever-zero sum.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tools.lint.finding import Finding
from tools.lint.index import (ProjectIndex, fstring_regex, iter_calls,
                              literal_str)

CHECKER = "pvar-spec"


def run(index: ProjectIndex) -> list[Finding]:
    specs = collect_specs(index)
    if specs is None:
        return []   # no catalogue in this tree — nothing to check
    spec_names, spec_mod, spec_line = specs
    findings: list[Finding] = []
    bumped: set[str] = set()

    for mod in index.modules.values():
        for call in iter_calls(mod.tree):
            arg = _count_arg(mod, call)
            if arg is None:
                continue
            lit = literal_str(arg)
            if lit is not None:
                if lit in spec_names:
                    bumped.add(lit)
                elif not mod.suppressed(call, "pvar"):
                    findings.append(Finding(
                        CHECKER, "undeclared-counter", lit,
                        f"counter {lit!r} bumped but not declared in "
                        f"_COUNTER_SPECS", mod.path, call.lineno))
                continue
            rx = fstring_regex(arg)
            if rx is not None:
                hits = {n for n in spec_names if re.match(rx, n)}
                if hits:
                    bumped |= hits
                elif not mod.suppressed(call, "pvar"):
                    findings.append(Finding(
                        CHECKER, "undeclared-counter", rx,
                        f"dynamic counter bump {rx!r} matches no "
                        f"_COUNTER_SPECS entry", mod.path, call.lineno))
        # counters["x"] subscripts also keep a spec alive
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Subscript) \
                    and _is_counters(node.value):
                lit = literal_str(node.slice)
                if lit is not None and lit in spec_names:
                    bumped.add(lit)

    for name in sorted(set(spec_names) - bumped):
        findings.append(Finding(
            CHECKER, "dead-pvar", name,
            f"_COUNTER_SPECS entry {name!r} is never bumped by any "
            f"count() call", spec_mod, spec_line.get(name, 0)))

    for name, path, line in collect_agg_metrics(index):
        if name not in spec_names:
            findings.append(Finding(
                CHECKER, "unknown-agg-metric", name,
                f"AGG_METRICS entry {name!r} names no _COUNTER_SPECS "
                f"counter — the per-job ompi_tpu_job_ sum on the scrape "
                f"endpoint would export forever-zero (renamed counter?)",
                path, line))
    return findings


def collect_specs(index: ProjectIndex
                  ) -> Optional[tuple[set[str], str, dict[str, int]]]:
    """The tree's ``_COUNTER_SPECS`` tuple → (names, path, name→line)."""
    for mod in index.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "_COUNTER_SPECS"
                            for t in node.targets)):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            names: set[str] = set()
            lines: dict[str, int] = {}
            for el in node.value.elts:
                if isinstance(el, (ast.Tuple, ast.List)) and el.elts:
                    nm = literal_str(el.elts[0])
                    if nm is not None:
                        names.add(nm)
                        lines[nm] = el.lineno
            return names, mod.path, lines
    return None


def collect_agg_metrics(index: ProjectIndex
                        ) -> list[tuple[str, str, int]]:
    """Every ``AGG_METRICS`` tuple's string entries →
    [(name, path, line)] — the aggregated-metric name family the DVM
    scrape endpoint exports per job."""
    out: list[tuple[str, str, int]] = []
    for mod in index.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "AGG_METRICS"
                            for t in node.targets)):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                continue
            for el in node.value.elts:
                nm = literal_str(el)
                if nm is not None:
                    out.append((nm, mod.path, el.lineno))
    return out


def _count_arg(mod, call: ast.Call) -> Optional[ast.expr]:
    """The name argument of a counter bump: ``trace.count(x)`` /
    ``trace_mod.count(x)`` / bare ``count(x)`` imported from the trace
    module.  Plain ``<anything-else>.count(x)`` (str/list methods) is
    not a bump."""
    f = call.func
    if not call.args:
        return None
    if isinstance(f, ast.Attribute) and f.attr == "count":
        recv = f.value
        if isinstance(recv, ast.Name) and "trace" in recv.id:
            return call.args[0]
        return None
    if isinstance(f, ast.Name) and f.id == "count":
        src = mod.from_imports.get("count")
        if src is not None and "trace" in src[0]:
            return call.args[0]
        if "count" in mod.functions:   # the trace module itself
            return call.args[0]
    return None


def _is_counters(node: ast.expr) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "counters"
    if isinstance(node, ast.Attribute):
        return node.attr == "counters"
    return False
