"""rml-tag — every tag sent over the rml bus has a recv handler.

``RmlNode._deliver`` drops a tagged message with no registered handler
(a verbose log line nobody reads) — so a sent-but-never-registered tag
is a protocol message that silently vanishes, and a TAG_* constant
nobody sends or receives is dead wire protocol.  Checks:

- ``unhandled-send``: a ``TAG_X`` constant passed to
  ``xcast/send_up/send_direct/send_hop/send_child`` with no
  ``register_recv(TAG_X, …)`` anywhere in the tree.
- ``dead-tag``: a ``TAG_X = "…"`` definition neither sent nor
  registered anywhere (wire protocol that can never fire).
- ``unsent-handler``: a handler registered for a tag nothing ever
  sends (dead dispatch arm).
- ``unknown-tag``: a ``TAG_*`` name sent or registered that no bus
  module defines (a typo'd constant would be an AttributeError at
  runtime — on the failure path where it was finally exercised).

Forwarded/variable tags (``xcast(tag, …)`` relays) are ignored, and
only ``TAG_*`` constants defined in a *bus module* (one whose classes
offer ``register_recv``) participate — the coll p2p tag space and
compat constants are MPI message tags, not bus wire protocol.
"""

from __future__ import annotations

import ast

from tools.lint.finding import Finding
from tools.lint.index import ProjectIndex, iter_calls

CHECKER = "rml-tag"
_SEND_FUNCS = ("xcast", "send_up", "send_direct", "send_hop",
               "send_child")


def run(index: ProjectIndex) -> list[Finding]:
    defined: dict[str, tuple[str, int]] = {}   # TAG name → (path, line)
    sent: dict[str, tuple[str, int]] = {}
    registered: dict[str, tuple[str, int]] = {}

    # TAG_* constants participate only when defined in a *bus* module —
    # one whose classes offer register_recv (rml.py).  Other TAG_
    # namespaces (the coll p2p tag space, compat constants) are MPI
    # message tags, not bus wire protocol.
    bus_modules = {
        mod.name for mod in index.modules.values()
        if any("register_recv" in ci.methods
               for ci in mod.classes.values())}
    for mod in index.modules.values():
        if mod.name in bus_modules:
            for node in mod.tree.body:
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) \
                                and tgt.id.startswith("TAG_"):
                            defined.setdefault(tgt.id,
                                               (mod.path, node.lineno))
    for mod in index.modules.values():
        for call in iter_calls(mod.tree):
            fname = _func_name(call)
            if fname in _SEND_FUNCS:
                for tag in _tag_args(call):
                    sent.setdefault(tag, (mod.path, call.lineno))
            elif fname == "register_recv":
                for tag in _tag_args(call):
                    registered.setdefault(tag, (mod.path, call.lineno))

    findings: list[Finding] = []
    for tag, (path, line) in sorted({**sent, **registered}.items()):
        if tag not in defined:
            findings.append(Finding(
                CHECKER, "unknown-tag", tag,
                f"{tag} is used on the bus but defined in no bus "
                f"module (typo?)", path, line))
    sent = {t: v for t, v in sent.items() if t in defined}
    registered = {t: v for t, v in registered.items() if t in defined}
    for tag, (path, line) in sorted(sent.items()):
        if tag not in registered:
            findings.append(Finding(
                CHECKER, "unhandled-send", tag,
                f"{tag} is sent but no register_recv handler exists "
                f"anywhere — the message is silently dropped",
                path, line))
    for tag, (path, line) in sorted(defined.items()):
        if tag not in sent and tag not in registered:
            findings.append(Finding(
                CHECKER, "dead-tag", tag,
                f"{tag} is defined but never sent or handled",
                path, line))
    for tag, (path, line) in sorted(registered.items()):
        if tag in defined and tag not in sent:
            findings.append(Finding(
                CHECKER, "unsent-handler", tag,
                f"a handler is registered for {tag} but nothing ever "
                f"sends it", path, line))
    return findings


def _func_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _tag_args(call: ast.Call) -> list[str]:
    out = []
    for arg in call.args:
        if isinstance(arg, ast.Name) and arg.id.startswith("TAG_"):
            out.append(arg.id)
        elif isinstance(arg, ast.Attribute) \
                and arg.attr.startswith("TAG_"):
            out.append(arg.attr)
    return out
