"""span-pairing — every recorder open has a close on every exit path.

The collective recorder's protocol is open/close: ``seq =
trace.coll_post(...)`` marks an operation in flight, and either
``trace.coll_done(...)`` (success) or ``trace.coll_err(...)`` (raise
path) must retire it.  A post without a done leaves the recorder head
"in flight" forever — the hang doctor then reports a phantom stuck
collective on a healthy rank; a post with a done but NO err path does
the same thing the first time the collective body raises.  The flight
recorder's span timing has the same shape: a ``t0 = trace.begin()``
stamp that no ``trace.complete(...)`` (or ``record_hist``) ever
consumes is a span opened and never closed — dead timing code.

Scope: the pairing may legitimately spread across methods (nbc's
request object posts in ``__init__`` and retires in its completion
callback) or across closures (persistent collectives retire inside
the started op's callback), so each rule checks the enclosing
function's full subtree first, then the enclosing class, then the
module — only a miss at EVERY level is a finding.

- ``unpaired-post``: ``coll_post`` with no reachable ``coll_done``.
- ``no-err-path``: ``coll_post`` + ``coll_done`` but no ``coll_err``
  anywhere in scope — the raise path leaks an in-flight op.
- ``unmatched-begin``: ``trace.begin()`` with no ``trace.complete``/
  ``record_hist`` in scope.

Waiver: ``# lint: span-ok`` on (or above) the opening call.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.lint.finding import Finding
from tools.lint.index import ModuleInfo, ProjectIndex, iter_calls

CHECKER = "span-pairing"

#: call names this checker pairs (open → closers)
_OPENERS = {
    "coll_post": (("coll_done",), ("coll_err",)),
    "begin": (("complete", "record_hist"), ()),
}
_ALL_NAMES = frozenset(
    {op for op in _OPENERS}
    | {n for done, err in _OPENERS.values() for n in done + err})


def run(index: ProjectIndex) -> list[Finding]:
    findings: list[Finding] = []
    for mod in index.modules.values():
        if mod.name.endswith("mpi.trace"):
            continue   # the definitions themselves, not call sites
        mod_names = _scan(mod, mod.tree)
        if not any(op in mod_names for op in _OPENERS):
            continue
        for fi, cls_node in _functions(mod):
            fn_calls = _scan_calls(mod, fi.node)
            fn_names = {n for n, _c in fn_calls}
            if not any(op in fn_names for op in _OPENERS):
                continue
            cls_names = (_scan(mod, cls_node)
                         if cls_node is not None else set())
            chain = (fn_names, cls_names, set(mod_names))
            for op, (done_names, err_names) in _OPENERS.items():
                if op not in fn_names:
                    continue
                call = next(c for n, c in fn_calls if n == op)
                if mod.suppressed(call, "span"):
                    continue
                closed = any(d in names for names in chain
                             for d in done_names)
                if not closed:
                    kind = ("unpaired-post" if op == "coll_post"
                            else "unmatched-begin")
                    closers = "/".join(done_names)
                    findings.append(Finding(
                        CHECKER, kind, f"{mod.name}.{fi.node.name}",
                        f"{op}() in {fi.node.name}() has no matching "
                        f"{closers} in the function, its class, or the "
                        f"module — the opened span/op never closes",
                        mod.path, call.lineno))
                elif err_names and not any(
                        e in names for names in chain
                        for e in err_names):
                    findings.append(Finding(
                        CHECKER, "no-err-path",
                        f"{mod.name}.{fi.node.name}",
                        f"{op}() in {fi.node.name}() pairs with "
                        f"{done_names[0]} but nothing calls "
                        f"{err_names[0]} — the first raise inside the "
                        f"collective body leaks an in-flight op (the "
                        f"hang doctor reads it as a phantom hang)",
                        mod.path, call.lineno))
    return findings


def _functions(mod: ModuleInfo):
    """Every indexed function with its enclosing class node (None for
    module-level defs).  Nested closures are NOT listed separately —
    they are part of their enclosing function's subtree."""
    for fi in mod.functions.values():
        yield fi, None
    for ci in mod.classes.values():
        for fi in ci.methods.values():
            yield fi, ci.node


def _scan(mod: ModuleInfo, tree: ast.AST) -> set[str]:
    return {n for n, _c in _scan_calls(mod, tree)}


def _scan_calls(mod: ModuleInfo,
                tree: ast.AST) -> list[tuple[str, ast.Call]]:
    """Trace-module recorder calls in the subtree → [(name, call)]."""
    out: list[tuple[str, ast.Call]] = []
    for call in iter_calls(tree):
        name = _trace_call(mod, call)
        if name is not None:
            out.append((name, call))
    return out


def _trace_call(mod: ModuleInfo, call: ast.Call) -> Optional[str]:
    """``trace.coll_post(...)`` / ``trace_mod.begin()`` / bare names
    imported from the trace module → the call name; None otherwise
    (``str.count``-style lookalikes must not match)."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _ALL_NAMES:
        recv = f.value
        if isinstance(recv, ast.Name) and "trace" in recv.id:
            return f.attr
        return None
    if isinstance(f, ast.Name) and f.id in _ALL_NAMES:
        src = mod.from_imports.get(f.id)
        if src is not None and "trace" in src[0]:
            return f.id
    return None
