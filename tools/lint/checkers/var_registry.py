"""var-registry — every MCA config-var read names a registered variable.

``VarRegistry.get()`` raises ``KeyError`` on an unregistered name, so an
unregistered read is a latent crash on a code path nobody has driven
yet (registration happens at import time of the owning module; a read
in module A of a var registered by module B that A never imports is the
classic failure).  Checks:

- ``unregistered-read``: ``var_registry.get/lookup/set("x")`` /
  ``get_var/set_var("x")`` with no matching ``register_var`` anywhere
  in the tree.  F-string names become regexes and must match ≥1
  registered var.
- ``type-mismatch``: a read of a STRING/STRING_LIST-typed var wrapped
  directly in ``int()``/``float()`` — the coercion will raise on the
  default the moment the var is unset-but-truthy.
- ``unknown-env-read``: an ``OMPI_TPU_*`` environment variable read
  whose name is never *produced* anywhere (no env-dict key, no
  ``environ[...] =`` store, no declared constant) — a typo'd env name
  reads as silently-unset forever.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from tools.lint.finding import Finding
from tools.lint.index import (ModuleInfo, ProjectIndex, fstring_regex,
                              iter_calls, literal_str)

CHECKER = "var-registry"
ENV_PREFIX = "OMPI_TPU_"

#: numeric coercions that break on string-typed values
_NUMERIC_WRAPPERS = ("int", "float")
#: registry read/write entry points: attribute form + bare-import form
_REG_ATTR_FUNCS = ("get", "lookup", "set")
_REG_BARE_FUNCS = ("get_var", "set_var")


def run(index: ProjectIndex) -> list[Finding]:
    registered, dynamic = collect_registrations(index)
    findings: list[Finding] = []
    findings += _check_reads(index, registered, dynamic)
    findings += _check_env(index)
    return findings


# -- registration side -----------------------------------------------------

def collect_registrations(index: ProjectIndex
                          ) -> tuple[dict[str, str], list[str]]:
    """(full var name → registered type, dynamic-name regexes).

    Literal registrations land in the dict (synonyms included, mapped
    to the same type).  Registrations whose framework or name is
    computed (loops registering ``f"host_{name}_algorithm"``, the MCA
    framework-selection var built from ``self.name``) become anchored
    regexes with wildcards for the computed parts."""
    out: dict[str, str] = {}
    dynamic: list[str] = []
    for mod in index.modules.values():
        for call in iter_calls(mod.tree):
            if _call_name(call) != "register_var":
                continue
            args = call.args
            if len(args) < 2:
                continue
            fw, name = literal_str(args[0]), literal_str(args[1])
            vtype = _vtype_text(args[2]) if len(args) > 2 else ""
            if fw is not None and name is not None:
                # mirror Var.full_name exactly: keyed on FRAMEWORK
                # truthiness (a frameworkless var is just its name)
                full = f"{fw}_{name}" if fw else name
                out[full] = vtype
            else:
                fw_rx = _part_regex(args[0])
                nm_rx = _part_regex(args[1])
                # mirror Var.full_name: f"{fw}_{name}" when name else fw
                _add_dynamic(dynamic, f"^{fw_rx}_{nm_rx}$" if nm_rx
                             else f"^{fw_rx}_$")
            for kw in call.keywords:
                if kw.arg == "synonyms" \
                        and isinstance(kw.value, (ast.Tuple, ast.List)):
                    for el in kw.value.elts:
                        syn = literal_str(el)
                        if syn is not None:
                            out[syn] = vtype
                        else:
                            rx = _part_regex(el)
                            if rx:
                                _add_dynamic(dynamic, f"^{rx}$")
    return out, dynamic


def _add_dynamic(dynamic: list[str], rx: str) -> None:
    """Keep a dynamic-registration regex only when it retains SOME
    literal content — a pure-wildcard pattern ('^.+?$' from a fully
    computed synonym) would match every read and void the checker."""
    if rx.replace(".+?", "").strip("^$"):
        dynamic.append(rx)


def _part_regex(node: ast.expr) -> str:
    """One register_var argument → regex fragment: literals escaped,
    f-string interpolations and plain names become wildcards."""
    lit = literal_str(node)
    if lit is not None:
        return re.escape(lit)
    rx = fstring_regex(node)
    if rx is not None:
        return rx[1:-1]   # strip the anchors; caller re-anchors
    return ".+?"


def _vtype_text(node: ast.expr) -> str:
    lit = literal_str(node)
    if lit is not None:
        return lit
    if isinstance(node, ast.Attribute):       # VarType.DOUBLE
        return node.attr.lower()
    return ""


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


# -- read side -------------------------------------------------------------

def _registry_read_name(call: ast.Call) -> Optional[ast.expr]:
    """The name-argument node of a registry read/write, else None."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _REG_ATTR_FUNCS:
        recv = f.value
        recv_txt = recv.id if isinstance(recv, ast.Name) else (
            recv.attr if isinstance(recv, ast.Attribute) else "")
        if "registry" not in recv_txt:
            return None   # dict.get / env.get / etc.
        # only the VAR registry: pvar_registry.lookup takes pvar names
        if "var_registry" not in recv_txt or "pvar" in recv_txt:
            return None
        return call.args[0] if call.args else None
    if isinstance(f, ast.Name) and f.id in _REG_BARE_FUNCS:
        return call.args[0] if call.args else None
    return None


def _check_reads(index: ProjectIndex, registered: dict[str, str],
                 dynamic: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    names = sorted(registered)
    for mod in index.modules.values():
        wrappers = _numeric_wrapper_map(mod)
        for call in iter_calls(mod.tree):
            arg = _registry_read_name(call)
            if arg is None:
                continue
            lit = literal_str(arg)
            if lit is not None:
                if lit not in registered \
                        and not any(re.match(rx, lit)
                                    for rx in dynamic):
                    if mod.suppressed(call, "var"):
                        continue
                    findings.append(Finding(
                        CHECKER, "unregistered-read", lit,
                        f"config var {lit!r} is read but never "
                        f"registered (register_var)",
                        mod.path, call.lineno))
                elif lit in registered:
                    findings += _type_check(mod, call, lit,
                                            registered[lit], wrappers)
                continue
            rx = fstring_regex(arg)
            if rx is not None:
                # a dynamic read matches a literal registration, or a
                # dynamic registration with the same literal skeleton
                if not any(re.match(rx, n) for n in names) \
                        and not any(_skeleton(rx) == _skeleton(d)
                                    for d in dynamic):
                    if mod.suppressed(call, "var"):
                        continue
                    findings.append(Finding(
                        CHECKER, "unregistered-read", rx,
                        f"dynamic config-var read {rx!r} matches no "
                        f"registered variable",
                        mod.path, call.lineno))
            # non-literal, non-f-string args are uncheckable; skip
    return findings


def _skeleton(rx: str) -> str:
    """A name-regex reduced to its literal skeleton (wildcards
    unified) so dynamic reads and dynamic registrations compare."""
    return rx.replace(".+?", "*")


def _numeric_wrapper_map(mod: ModuleInfo) -> dict[int, str]:
    """id(inner read call) → wrapping numeric coercion name, for reads
    written as ``int(var_registry.get("x"))`` (also through a single
    ``or`` default: ``int(get(...) or 0)``)."""
    out: dict[int, str] = {}
    for call in iter_calls(mod.tree):
        fn = call.func
        if not (isinstance(fn, ast.Name)
                and fn.id in _NUMERIC_WRAPPERS and call.args):
            continue
        inner = call.args[0]
        if isinstance(inner, ast.BoolOp):
            inner = inner.values[0]
        if isinstance(inner, ast.Call):
            out[id(inner)] = fn.id
    return out


def _type_check(mod: ModuleInfo, call: ast.Call, name: str, vtype: str,
                wrappers: dict[int, str]) -> list[Finding]:
    wrap = wrappers.get(id(call))
    if wrap and vtype in ("string", "string_list"):
        if mod.suppressed(call, "var"):
            return []
        return [Finding(
            CHECKER, "type-mismatch", name,
            f"{vtype}-typed var {name!r} wrapped in {wrap}() — "
            f"coercion raises on non-numeric values",
            mod.path, call.lineno)]
    return []


# -- environment side ------------------------------------------------------

def _check_env(index: ProjectIndex) -> list[Finding]:
    produced: set[str] = set()
    reads: list[tuple[ModuleInfo, ast.AST, str]] = []
    for mod in index.modules.values():
        for node in ast.walk(mod.tree):
            # reads: environ.get("X") / environ["X"] loads
            if isinstance(node, ast.Call):
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in ("get", "pop", "setdefault")
                        and _is_environ(f.value) and node.args):
                    nm = _env_name(mod, node.args[0])
                    if nm:
                        if f.attr == "get":
                            reads.append((mod, node, nm))
                        else:   # pop/setdefault touch implies produced
                            produced.add(nm)
            elif isinstance(node, ast.Subscript):
                nm = _env_name(mod, node.slice)
                if not nm:
                    continue
                if _is_environ(node.value):
                    if isinstance(node.ctx, ast.Store):
                        produced.add(nm)
                    elif isinstance(node.ctx, ast.Del):
                        produced.add(nm)
                    else:
                        reads.append((mod, node, nm))
                elif isinstance(node.ctx, ast.Store):
                    # env["X"] = … on any dict builds a child environment
                    produced.add(nm)
            elif isinstance(node, ast.Dict):
                for k in node.keys:
                    nm = k is not None and _env_name(mod, k)
                    if nm:
                        produced.add(nm)
            elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
                # passthrough tables: ("OMPI_TPU_RESTART", …)
                for el in node.elts:
                    nm = _env_name(mod, el)
                    if nm:
                        produced.add(nm)
            elif isinstance(node, ast.Assign):
                v = literal_str(node.value)
                if v and v.startswith(ENV_PREFIX):
                    produced.add(v)   # ENV_URI = "OMPI_TPU_HNP_URI"
    findings = []
    for mod, node, nm in reads:
        if nm.startswith(ENV_PREFIX + "MCA_"):
            continue   # the registry's own env channel, always dynamic
        if nm not in produced and not mod.suppressed(node, "env"):
            findings.append(Finding(
                CHECKER, "unknown-env-read", nm,
                f"env var {nm!r} is read but never produced or "
                f"declared anywhere in the tree (typo?)",
                mod.path, getattr(node, "lineno", 0)))
    return findings


def _is_environ(node: ast.expr) -> bool:
    txt = ""
    if isinstance(node, ast.Attribute):
        txt = node.attr
    elif isinstance(node, ast.Name):
        txt = node.id
    return txt == "environ"


def _env_name(mod: ModuleInfo, node: ast.expr) -> Optional[str]:
    lit = literal_str(node)
    if lit is None and isinstance(node, ast.Name):
        lit = mod.constants.get(node.id)
    if lit is not None and lit.startswith(ENV_PREFIX):
        return lit
    return None
