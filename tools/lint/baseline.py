"""Baseline file — grandfathered findings.

Format (``tools/lint/baseline.json``)::

    {
      "findings": [
        {"fingerprint": "lock-order:cycle:a.B._lock->c.D._lock",
         "justification": "why this one is accepted"}
      ]
    }

A finding whose fingerprint appears here is reported as *grandfathered*
and does not fail the run.  Entries are expected to carry a
justification — an empty baseline is the goal state; a justified one is
the escape hatch for accepted-risk findings the fix would regress.
Stale entries (fingerprints no current finding produces) fail the run:
a fixed finding must leave the baseline with the fix, or the file rots
into a blanket waiver.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

from tools.lint.finding import Finding

DEFAULT_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


class Baseline:
    def __init__(self, entries: dict[str, str]) -> None:
        #: fingerprint → justification
        self.entries = entries

    @classmethod
    def load(cls, path: str | None) -> "Baseline":
        path = path or DEFAULT_PATH
        if not os.path.exists(path):
            return cls({})
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        entries: dict[str, str] = {}
        for ent in doc.get("findings", ()):
            entries[str(ent["fingerprint"])] = str(
                ent.get("justification", ""))
        return cls(entries)

    @staticmethod
    def write(path: str, findings: Iterable[Finding],
              keep: Optional[dict[str, str]] = None,
              ran: Optional[set[str]] = None) -> None:
        """Write ``findings`` as the baseline, preserving every
        justification in ``keep`` (fingerprint → text) and carrying
        over ``keep`` entries for checkers that did NOT run — a
        ``--checker`` subset rewrite must not delete other checkers'
        grandfathered findings.  ``ran`` is the set of checker names
        that executed (default: inferred from the findings — wrong for
        a ran-but-now-clean checker, so the driver passes it
        explicitly: a subset run that FIXED its findings must drop
        them, not carry them into a stale-entry failure)."""
        keep = keep or {}
        entries: dict[str, str] = {}
        ran_checkers = (set(ran) if ran is not None
                        else {f.checker for f in findings})
        for f in set(findings):
            entries[f.fingerprint] = keep.get(
                f.fingerprint, "TODO: justify or fix")
        for fp, just in keep.items():
            if fp.split(":", 1)[0] not in ran_checkers \
                    and fp not in entries:
                entries[fp] = just
        doc = {
            "findings": [
                {"fingerprint": fp, "justification": just}
                for fp, just in sorted(entries.items())
            ],
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")

    def split(self, findings: list[Finding]
              ) -> tuple[list[Finding], list[Finding], list[str]]:
        """→ (new, grandfathered, stale-fingerprints)."""
        new, old = [], []
        seen: set[str] = set()
        for f in findings:
            if f.fingerprint in self.entries:
                old.append(f)
                seen.add(f.fingerprint)
            else:
                new.append(f)
        stale = sorted(set(self.entries) - seen)
        return new, old, stale
