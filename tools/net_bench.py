"""Inter-node transport microbench: native tcp plane vs python plane.

Pingpong latency and small-message rate over real loopback sockets
between two ranks forced to ``--mca btl self,tcp`` (no shm, no proc
shortcut — the same frames a cross-host pair would exchange, minus the
wire).  Both planes run in the SAME world, alternating per rep: every
rank flips ``btl_tcp_native`` between barriers, so the two
configurations share scheduling fate (the var is read per call — the
sockets never change, only who drains them).

Two world shapes:

- default: **loopback fake-host worlds** — ``tpurun --plm sim --hosts
  2`` spawns each rank as its own process on a distinct simulated host
  (shm refuses across the OMPI_TPU_FAKE_HOST boundary), so every rank
  owns a full interpreter.  This is the deployment shape the native
  plane exists for: the GIL the native writer/poller release belongs
  to application code, not to the other rank's transport.
- ``--inproc``: the two ranks are threads in one interpreter (the test
  harness shape).  Useful as a floor/contrast: here both planes fight
  over ONE GIL and the native plane's release only helps the peer.

Per row: p50/p99 of per-op RTT over a synchronized loop, best-of-reps
per mode.  The msgrate burst additionally captures the
``btl_tcp_native_batched_frames_total / btl_tcp_native_writes_total``
delta ratio — >1 means the submission ring actually coalesced frames
into batched writev calls, the whole point of the native writer.

Rows append to ``NET_BENCH.jsonl`` (the PACK_BENCH.jsonl convention).

Run: ``python tools/net_bench.py [--quick] [--inproc]
[--guard|--guard-kill]``.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ompi_tpu.core.config import var_registry  # noqa: E402
from ompi_tpu.mpi import trace  # noqa: E402

_OUT = os.path.join(REPO, "NET_BENCH.jsonl")


# ---------------------------------------------------------------- bodies
# Run identically under both world shapes.  Every rank flips the var:
# in a fake-host world each process owns its own registry; in-process
# the two threads just write the same value twice.

def _pp_samples(comm, nbytes: int, iters: int, reps: int):
    """Pingpong RTT samples per mode; returns the sample dict on rank 0,
    None elsewhere."""
    samples: dict[bool, list[list[float]]] = {True: [], False: []}
    x = np.zeros(max(nbytes, 1), dtype=np.uint8)[:nbytes]
    if comm.rank == 0 and nbytes:
        x[:] = 42
    for _rep in range(reps):
        for native in (True, False):
            var_registry.set("btl_tcp_native", native)
            comm.barrier()
            # warm the route/plane outside the timed loop
            if comm.rank == 0:
                comm.send(x, dest=1, tag=1)
                comm.recv(x, source=1, tag=2)
            else:
                comm.recv(x, source=0, tag=1)
                comm.send(x, dest=0, tag=2)
            ts = []
            for _ in range(iters):
                if comm.rank == 0:
                    t0 = time.perf_counter()
                    comm.send(x, dest=1, tag=1)
                    comm.recv(x, source=1, tag=2)
                    ts.append(time.perf_counter() - t0)
                else:
                    comm.recv(x, source=0, tag=1)
                    comm.send(x, dest=0, tag=2)
            if comm.rank == 0:
                samples[native].append(ts)
    comm.barrier()
    return samples if comm.rank == 0 else None


def _mr_samples(comm, nbytes: int, burst: int, reps: int):
    """Msgrate burst: rank 0 isends `burst` frames, rank 1 pre-posts the
    recvs and acks; returns (rates, native counter deltas) on rank 0."""
    rates: dict[bool, list[float]] = {True: [], False: []}
    deltas: list[dict] = []
    x = np.zeros(nbytes, dtype=np.uint8)
    for _rep in range(reps):
        for native in (True, False):
            var_registry.set("btl_tcp_native", native)
            comm.barrier()
            if comm.rank == 0:
                before = {k: trace.counters[k] for k in
                          ("btl_tcp_native_writes_total",
                           "btl_tcp_native_batched_frames_total")}
                t0 = time.perf_counter()
                reqs = [comm.isend(x, dest=1, tag=i % 8)
                        for i in range(burst)]
                for r in reqs:
                    r.wait()
                # the far side acks completion via a frame so the
                # rate includes delivery, not just enqueue
                comm.recv(source=1, tag=99)
                dt = time.perf_counter() - t0
                rates[native].append(burst / dt)
                if native:
                    deltas.append({
                        k: trace.counters[k] - v
                        for k, v in before.items()})
            else:
                reqs = [comm.irecv(np.empty(nbytes, np.uint8),
                                   source=0, tag=i % 8)
                        for i in range(burst)]
                for r in reqs:
                    r.wait()
                comm.send(np.zeros(1, np.uint8), dest=0, tag=99)
    comm.barrier()
    return (rates, deltas) if comm.rank == 0 else None


# ------------------------------------------------------------ row builders

def _pp_rows(samples, nbytes: int, iters: int, reps: int,
             world: str) -> list[dict]:
    rows = []
    for native in (True, False):
        best = min(samples[native], key=statistics.median)
        rows.append({
            "bench": "tcp_pingpong", "world": world,
            "mode": "native" if native else "python",
            "payload_bytes": nbytes,
            "iters": iters, "reps": reps,
            "p50_us": round(statistics.median(best) * 1e6, 1),
            "p99_us": round(
                sorted(best)[max(0, int(len(best) * 0.99) - 1)] * 1e6, 1),
        })
    return rows


def _mr_rows(rates, deltas, nbytes: int, burst: int, reps: int,
             world: str) -> list[dict]:
    writes = sum(d["btl_tcp_native_writes_total"] for d in deltas)
    frames = sum(d["btl_tcp_native_batched_frames_total"] for d in deltas)
    rows = []
    for native in (True, False):
        rows.append({
            "bench": "tcp_msgrate", "world": world,
            "mode": "native" if native else "python",
            "payload_bytes": nbytes, "burst": burst, "reps": reps,
            "msgs_per_s": round(max(rates[native])),
            **({"writes": writes, "batched_frames": frames,
                "batch_ratio": round(frames / writes, 2) if writes else 0.0}
               if native else {}),
        })
    return rows


# ----------------------------------------------------- fake-host worlds

def _child_main(args) -> None:
    """Rank program inside a tpurun fake-host world: run the body, rank
    0 prints one NBDATA json line the parent parses out of the IOF."""
    import ompi_tpu

    comm = ompi_tpu.init()
    if args.child == "pingpong":
        s = _pp_samples(comm, args.nbytes, args.iters, args.reps)
        data = s and {"native": s[True], "python": s[False]}
    else:
        r = _mr_samples(comm, args.nbytes, args.burst, args.reps)
        data = r and {"rates": {"native": r[0][True], "python": r[0][False]},
                      "deltas": r[1]}
    if data is not None:
        print("NBDATA " + json.dumps(data), flush=True)
    ompi_tpu.finalize()


def _fakehost_world(child: str, timeout: float = 600.0, **kw) -> dict:
    """Spawn one 2-rank / 2-fake-host world via tpurun and return rank
    0's NBDATA payload."""
    env = dict(os.environ)
    env.pop("OMPI_TPU_RANK", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "ompi_tpu.tools.tpurun",
           "-np", "2", "--plm", "sim", "--hosts", "2",
           "--mca", "btl", "self,tcp", "--",
           sys.executable, os.path.abspath(__file__), "--child", child]
    for k, v in kw.items():
        cmd += [f"--{k}", str(v)]
    r = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=timeout, env=env, cwd=REPO)
    if r.returncode != 0:
        raise RuntimeError(
            f"fake-host world failed rc={r.returncode}:\n"
            f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")
    for line in r.stdout.splitlines():
        if "NBDATA " in line:  # IOF may prefix a [job,rank] tag
            return json.loads(line.split("NBDATA ", 1)[1])
    raise RuntimeError("no NBDATA line in world output:\n"
                       + r.stdout[-2000:])


def bench_pingpong_fakehost(nbytes: int, iters: int, reps: int) -> list[dict]:
    d = _fakehost_world("pingpong", nbytes=nbytes, iters=iters, reps=reps)
    return _pp_rows({True: d["native"], False: d["python"]},
                    nbytes, iters, reps, world="fakehost")


def bench_msgrate_fakehost(nbytes: int, burst: int, reps: int) -> list[dict]:
    d = _fakehost_world("msgrate", nbytes=nbytes, burst=burst, reps=reps)
    return _mr_rows({True: d["rates"]["native"], False: d["rates"]["python"]},
                    d["deltas"], nbytes, burst, reps, world="fakehost")


# ------------------------------------------------------ in-process world

def _run_world(n: int, fn, timeout: float = 600.0) -> list:
    """In-process n-rank world (tests/mpi/harness.run_ranks, inlined so
    the tool has no test-tree import)."""
    from ompi_tpu.mpi.comm import Communicator
    from ompi_tpu.mpi.group import Group
    from ompi_tpu.mpi.pml import PmlOb1

    pmls = [PmlOb1(r) for r in range(n)]
    addrs = {r: p.address for r, p in enumerate(pmls)}
    for p in pmls:
        p.set_peers(addrs)
    comms = [Communicator(Group(range(n)), cid=0, pml=pmls[r],
                          my_world_rank=r, name=f"netbench{n}")
             for r in range(n)]
    results: list = [None] * n
    errors: list = []

    def runner(rank: int) -> None:
        try:
            results[rank] = fn(comms[rank])
        except BaseException as e:  # noqa: BLE001
            errors.append((rank, e))

    threads = [threading.Thread(target=runner, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    try:
        if any(t.is_alive() for t in threads):
            raise TimeoutError(f"bench ranks hung (errors: {errors})")
        if errors:
            raise errors[0][1]
    finally:
        if not any(t.is_alive() for t in threads):
            for p in pmls:
                p.close()
    return results


def bench_pingpong_inproc(nbytes: int, iters: int, reps: int) -> list[dict]:
    res = _run_world(2, lambda c: _pp_samples(c, nbytes, iters, reps))
    return _pp_rows(res[0], nbytes, iters, reps, world="inproc")


def bench_msgrate_inproc(nbytes: int, burst: int, reps: int) -> list[dict]:
    rates, deltas = _run_world(
        2, lambda c: _mr_samples(c, nbytes, burst, reps))[0]
    return _mr_rows(rates, deltas, nbytes, burst, reps, world="inproc")


def main() -> None:
    ap = argparse.ArgumentParser(
        description="native-vs-python tcp plane latency/msgrate")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke sizing: fewer sizes, fewer iters")
    ap.add_argument("--inproc", action="store_true",
                    help="two ranks as threads in ONE interpreter "
                    "(shared GIL) instead of fake-host processes")
    ap.add_argument("--guard", action="store_true",
                    help="preflight: refuse to bench when hours-old "
                    "PPID-1 orphaned ompi_tpu processes poison the box")
    ap.add_argument("--guard-kill", action="store_true",
                    help="like --guard but SIGKILL the orphans and "
                    "proceed")
    ap.add_argument("--out", default=_OUT)
    # internal: rank-program mode inside a tpurun fake-host world
    ap.add_argument("--child", choices=("pingpong", "msgrate"),
                    help=argparse.SUPPRESS)
    ap.add_argument("--nbytes", type=int, help=argparse.SUPPRESS)
    ap.add_argument("--iters", type=int, help=argparse.SUPPRESS)
    ap.add_argument("--reps", type=int, help=argparse.SUPPRESS)
    ap.add_argument("--burst", type=int, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        _child_main(args)
        return

    if args.guard or args.guard_kill:
        from tools import killorphans

        if not killorphans.preflight("net_bench", kill=args.guard_kill):
            sys.exit(2)

    if args.quick:
        sizes = [1 << 10, 64 << 10]
        iters, reps, burst = 100, 2, 500
    else:
        sizes = [1 << 10, 64 << 10, 1 << 20]
        iters, reps, burst = 300, 3, 2000

    world = "inproc" if args.inproc else "fakehost"
    pingpong = bench_pingpong_inproc if args.inproc else \
        bench_pingpong_fakehost
    msgrate = bench_msgrate_inproc if args.inproc else bench_msgrate_fakehost

    if args.inproc:
        # registers the btl framework-selection var as a side effect
        from ompi_tpu.mpi import pml as _pml  # noqa: F401

        var_registry.set("btl_", "self,tcp")
    rows: list[dict] = []
    try:
        for nbytes in sizes:
            it = max(20, iters // 10) if nbytes >= (1 << 20) else iters
            rows += pingpong(nbytes, it, reps)
        rows += msgrate(512, burst, reps)
    finally:
        if args.inproc:
            var_registry.set("btl_", "")
            var_registry.set("btl_tcp_native", True)

    with open(args.out, "a", encoding="utf-8") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"{len(rows)} rows -> {args.out}")

    by = {(r["bench"], r["payload_bytes"], r["mode"]): r for r in rows}
    wins = 0
    for nbytes in sizes:
        nat = by[("tcp_pingpong", nbytes, "native")]
        py = by[("tcp_pingpong", nbytes, "python")]
        speedup = py["p50_us"] / nat["p50_us"] if nat["p50_us"] else 0.0
        wins += speedup >= 1.5
        print(f"pingpong {nbytes:>8}B: native {nat['p50_us']:>7}us  "
              f"python {py['p50_us']:>7}us  ({speedup:.2f}x)")
    nat = by[("tcp_msgrate", 512, "native")]
    py = by[("tcp_msgrate", 512, "python")]
    print(f"msgrate 512B x{nat['burst']}: native {nat['msgs_per_s']} "
          f"msg/s  python {py['msgs_per_s']} msg/s  "
          f"batch_ratio {nat.get('batch_ratio')}")
    ok = wins >= 2 and (nat.get("batch_ratio") or 0) > 1
    print(f"acceptance ({world}): {'PASS' if ok else 'FAIL'} "
          f"(pingpong >=1.5x at {wins} rows; batching "
          f"{nat.get('batch_ratio')})")


if __name__ == "__main__":
    main()
