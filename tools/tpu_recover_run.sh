#!/bin/bash
# Wait for the axon tunnel to come back, then run the queued TPU work:
# (1) flagship configs validating the degenerate-collective elision,
# (2) full bench (refreshes preflight evidence + populates the
#     persistent compile cache the driver's end-of-round run will hit),
# (3) step-time breakdown, (4) the new feature rows.
# State in /tmp/tpurecover/.
mkdir -p /tmp/tpurecover
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
while true; do
  if timeout 420 python -c "
import jax, numpy as np
x = jax.jit(lambda a: a*2)(np.ones(8, np.float32))
assert jax.devices()[0].platform == 'tpu'
print(float(x[0]))" >/tmp/tpurecover/probe.log 2>&1; then
    echo "$(date -u +%FT%TZ) tpu up — sweep" >> /tmp/tpurecover/status
    python tools/mfu_sweep.py b16-xla-ce256-chain32 b16-xla-ce256-chain64 \
      >> /tmp/tpurecover/sweep.log 2>&1
    echo "$(date -u +%FT%TZ) sweep rc=$? — bench" >> /tmp/tpurecover/status
    python bench.py > /tmp/tpurecover/bench.out 2> /tmp/tpurecover/bench.err
    echo "$(date -u +%FT%TZ) bench rc=$? — breakdown" >> /tmp/tpurecover/status
    python tools/step_breakdown.py >> /tmp/tpurecover/breakdown.log 2>&1
    echo "$(date -u +%FT%TZ) breakdown rc=$? — feature rows" >> /tmp/tpurecover/status
    python tools/mfu_sweep.py b16-xla-pbf16-chain32 b32-accum2-xla-chain16 \
      >> /tmp/tpurecover/sweep.log 2>&1
    echo "$(date -u +%FT%TZ) all done rc=$?" >> /tmp/tpurecover/status
    break
  fi
  echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpurecover/status
  sleep 120
done
