#!/bin/bash
# Wait for the axon tunnel to come back, then (1) validate the
# degenerate-collective elision on the flagship configs, (2) run the
# full bench to refresh preflight evidence and populate the persistent
# compile cache for the driver's end-of-round run.
# State in /tmp/tpurecover/.
mkdir -p /tmp/tpurecover
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
while true; do
  if timeout 180 python -c "
import jax, numpy as np
x = jax.jit(lambda a: a*2)(np.ones(8, np.float32))
assert jax.devices()[0].platform == 'tpu'
print(float(x[0]))" >/tmp/tpurecover/probe.log 2>&1; then
    echo "$(date -u +%FT%TZ) tpu up — sweep" >> /tmp/tpurecover/status
    python tools/mfu_sweep.py b16-xla-ce256-chain32 b16-xla-ce256-chain64 \
      >> /tmp/tpurecover/sweep.log 2>&1
    echo "$(date -u +%FT%TZ) sweep rc=$? — bench" >> /tmp/tpurecover/status
    python bench.py > /tmp/tpurecover/bench.out 2> /tmp/tpurecover/bench.err
    echo "$(date -u +%FT%TZ) bench rc=$?" >> /tmp/tpurecover/status
    break
  fi
  echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpurecover/status
  sleep 180
done
