#!/bin/bash
# Wait for the axon tunnel to come back, then run the queued round-5 TPU
# work in priority order:
#   (1) flagship configs measuring the degenerate-collective elision
#       (chain32 — the biggest unmeasured MFU lever) + the chain64 best,
#   (2) full bench (slope-timed bandwidth rows incl. the new hbm_copy
#       calibration; refreshes evidence + fills the persistent compile
#       cache the driver's end-of-round run will hit),
#   (3) step-time breakdown + an xprof trace artifact of the flagship,
#   (4) feature rows: param-bf16, grad-accum, flash block sizes, pallas
#       backward, cost analysis.
# Artifacts land in-repo (MFU_SWEEP.jsonl appends; raw logs under
# /tmp/tpurecover/) and the in-repo ones are committed so they survive
# session end.  State in /tmp/tpurecover/.
mkdir -p /tmp/tpurecover
cd /root/repo
export JAX_COMPILATION_CACHE_DIR=/root/repo/.jax_cache
while true; do
  if timeout 420 python -c "
import jax, numpy as np
x = jax.jit(lambda a: a*2)(np.ones(8, np.float32))
assert jax.devices()[0].platform == 'tpu'
print(float(x[0]))" >/tmp/tpurecover/probe.log 2>&1; then
    echo "$(date -u +%FT%TZ) tpu up — elision sweep" >> /tmp/tpurecover/status
    python tools/mfu_sweep.py b16-xla-ce256-chain32 b16-xla-ce256-chain64 \
      >> /tmp/tpurecover/sweep.log 2>&1
    echo "$(date -u +%FT%TZ) sweep rc=$? — bench" >> /tmp/tpurecover/status
    python bench.py > /tmp/tpurecover/bench.out 2> /tmp/tpurecover/bench.err
    echo "$(date -u +%FT%TZ) bench rc=$? — breakdown" >> /tmp/tpurecover/status
    python tools/step_breakdown.py >> /tmp/tpurecover/breakdown.log 2>&1
    echo "$(date -u +%FT%TZ) breakdown rc=$? — xprof" >> /tmp/tpurecover/status
    timeout 1800 python tools/xprof_capture.py --steps 2 \
      --out /root/repo/xprof_trace \
      > /tmp/tpurecover/xprof.out 2> /tmp/tpurecover/xprof.err
    echo "$(date -u +%FT%TZ) xprof rc=$? — feature rows" >> /tmp/tpurecover/status
    python tools/mfu_sweep.py b16-xla-pbf16-chain32 b32-accum2-xla-chain16 \
      b16-flash-bq256 b16-flash-bk512 b16-chunk128-dots-pbwd \
      b8-s2048-xla-chain16 b8-s2048-flash-chain16 b4-s4096-flash-chain16 \
      >> /tmp/tpurecover/sweep.log 2>&1
    echo "$(date -u +%FT%TZ) features rc=$? — cost" >> /tmp/tpurecover/status
    timeout 900 python tools/cost_analysis.py >> /tmp/tpurecover/cost.log 2>&1
    # preserve the raw driver-methodology record in-repo so it survives
    # even if the interactive session is gone when the tunnel revives.
    # stdout streams carry log lines before the record — the committed
    # .json files get exactly the final JSON line of each
    tail -n 1 /tmp/tpurecover/bench.out > BENCH_TPU_RECOVERY_RUN.json 2>/dev/null
    tail -n 1 /tmp/tpurecover/xprof.out > XPROF_SUMMARY.json 2>/dev/null
    git add MFU_SWEEP.jsonl BENCH_MATRIX.json BENCH_TPU_RECOVERY_RUN.json \
      XPROF_SUMMARY.json xprof_trace ompi_tpu/mpi/coll/xla_measured_rules.conf \
      2>/dev/null
    git commit -m "TPU recovery run: elision sweep, slope-timed bench, xprof trace, feature rows" \
      >> /tmp/tpurecover/status 2>&1
    echo "$(date -u +%FT%TZ) all done" >> /tmp/tpurecover/status
    break
  fi
  echo "$(date -u +%FT%TZ) tpu down" >> /tmp/tpurecover/status
  sleep 120
done
