"""Pack/unpack convertor microbench — one JSON line per config.

Times the host datatype hot path over run counts {1, 1k, 100k, 1M} for
the two plan families the run-coalescing convertor compiles to:

- ``vector``:   FLOAT64.vector(n, 1, 2) — an affine layout, plans to a
                strided progression (zero per-run metadata).
- ``hindexed``: gapped 8B runs — plans to coalesced absolute (offsets,
                lengths) arrays with the uniform-length fast path.
- ``ragged``:   alternating 8B/16B runs — the generic wide-run memcpy
                loop (no fixed-width specialization possible).

Per config it reports the cold first pack (constructor + commit + plan
compile + copy), then slope-timed steady-state ``pack_into`` (the
memoryview variant the transports use — no bytes materialization),
``pack`` (bytes-returning) and ``unpack``.  Slope timing: the same
call at two rep counts, cost = (t_hi - t_lo) / (reps_hi - reps_lo), so
per-call constants cancel (the bench.py two-point method, host-side).

Rows append to ``PACK_BENCH.jsonl`` next to the repo root
(MFU_SWEEP.jsonl style — append-only, one JSON object per line) so the
92 ms → target headline stays reproducible and future regressions are
visible.  Run: ``python tools/pack_bench.py [--runs 1,1000,...]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ompi_tpu.mpi import datatype as dt  # noqa: E402
from ompi_tpu.mpi.datatype import DerivedDatatype  # noqa: E402

_OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "PACK_BENCH.jsonl")


def _make(layout: str, runs: int):
    """(datatype, buffer bytes needed) for ``runs`` runs, committed —
    construction + commit time is the COLD number, so this is timed."""
    if layout == "vector":
        return dt.FLOAT64.vector(runs, 1, 2).commit()
    if layout == "hindexed":
        # gapped, non-abutting 8B runs (offset 4 keeps the item-boundary
        # merge away so the run count stays honest)
        offs = np.arange(runs, dtype=np.int64) * 24 + 4
        cnts = np.full(runs, 8, np.int64)
        t = DerivedDatatype(dt.BYTE, (offs, cnts), pattern_unit="bytes",
                            name=f"hindexed({runs})")
        return t.commit()
    if layout == "ragged":
        offs = np.arange(runs, dtype=np.int64) * 32 + 4
        cnts = np.where(np.arange(runs) % 2 == 0, 8, 16).astype(np.int64)
        t = DerivedDatatype(dt.BYTE, (offs, cnts), pattern_unit="bytes",
                            name=f"ragged({runs})")
        return t.commit()
    raise ValueError(layout)


def _slope_ms(fn, reps_lo: int, reps_hi: int) -> float:
    """Per-call milliseconds by the two-point slope (constants cancel)."""
    fn()   # warm

    def timed(reps: int) -> float:
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(reps):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo, t_hi = timed(reps_lo), timed(reps_hi)
    return max(t_hi - t_lo, 1e-9) / (reps_hi - reps_lo) * 1e3


def bench_config(layout: str, runs: int) -> dict:
    t0 = time.perf_counter()
    t = _make(layout, runs)                      # constructor + commit =
    commit_ms = (time.perf_counter() - t0) * 1e3  # descriptor + plan compile
    plan = t.pack_plan(1)
    src = np.random.default_rng(0).integers(
        0, 256, max(plan.span, 8)).astype(np.uint8)
    t0 = time.perf_counter()
    cold = t.pack(src, 1)                        # first pack, plan warm
    first_pack_ms = (time.perf_counter() - t0) * 1e3
    total = len(cold)
    out = np.empty(total, np.uint8)
    dst = np.empty_like(src)
    reps = (2, 10) if runs >= 100_000 else (10, 50)
    row = {
        "bench": "pack_bench",
        "layout": layout,
        "runs": runs,
        "payload_bytes": total,
        "plan": t.pack_plan(1).kind,
        "native": dt._native_convertor(max(total, 1 << 30)) is not None,
        "commit_ms": round(commit_ms, 3),
        "first_pack_ms": round(first_pack_ms, 3),
        "pack_into_ms": round(_slope_ms(
            lambda: t.pack_into(src, 1, out), *reps), 4),
        "pack_ms": round(_slope_ms(lambda: t.pack(src, 1), *reps), 4),
        "unpack_ms": round(_slope_ms(
            lambda: t.unpack(out, dst, 1), *reps), 4),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    row["pack_into_gibps"] = round(
        total / (row["pack_into_ms"] / 1e3) / 2**30, 3)
    return row


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--runs", default="1,1000,100000,1000000",
                    help="comma-separated run counts")
    ap.add_argument("--layouts", default="vector,hindexed,ragged")
    ap.add_argument("--out", default=_OUT)
    args = ap.parse_args()
    run_counts = [int(x) for x in args.runs.split(",") if x.strip()]
    rows = []
    for layout in args.layouts.split(","):
        for n in run_counts:
            row = bench_config(layout, n)
            rows.append(row)
            print(json.dumps(row), flush=True)
            with open(args.out, "a") as f:
                f.write(json.dumps(row) + "\n")
    head = [r for r in rows if r["runs"] == max(run_counts)]
    for r in head:
        print(f"# {r['layout']} @ {r['runs']} runs: "
              f"pack_into {r['pack_into_ms']}ms "
              f"({r['pack_into_gibps']} GiB/s), commit+first "
              f"{r['commit_ms']}+{r['first_pack_ms']}ms", file=sys.stderr)


if __name__ == "__main__":
    main()
