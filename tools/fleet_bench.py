"""Control-plane scale bench: the simulated fleet under correlated loss.

Boots :class:`ompi_tpu.testing.simfleet.SimFleet` worlds of increasing
size (all in ONE process — stub ranks, real HNP) and measures what the
control plane costs as the world grows:

- **boot_s** — register → wire → ready for the whole tree
- **rack kill** (``--kill-frac`` of the daemons, mid-tree band, one
  tick): **adopt_s** convergence time, **reparent_epochs /
  reparent_orphans / reparent_frames** — the storm-bound assertion is
  frames == orphans + adopter-groups, one epoch per correlated loss
- **false_positive_ranks** — ranks declared dead whose daemon survived
  (must be 0: the heartbeat grace + world-scaled windows at work)
- **doctor** fleet capture: **doctor_rows** (the O(hosts ×
  doctor_rows_per_daemon) fan-in bound) and **doctor_s**
- **metrics storm** (every daemon pushes a full snapshot in one wave):
  **agg_merges / agg_sheds / agg_shed_rows** and **merge_ns_total** —
  the shed-and-count valve's ledger

Rows append to ``FLEET_BENCH.jsonl`` (the PACK_BENCH.jsonl convention).
``--assert`` turns the CI invariants into the exit code, so the
fleet-smoke job fails loudly instead of shipping a regression:
adoption under ``--adopt-budget`` seconds, zero false-positive rank
deaths, zero self-failed daemons, exactly one reparent epoch, and
frames <= 2x orphans.

Run: ``python tools/fleet_bench.py [--quick] [--assert]
[--worlds 25,50,100] [--guard|--guard-kill]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

_OUT = os.path.join(REPO, "FLEET_BENCH.jsonl")


def bench_world(n_daemons: int, ranks_per_daemon: int, kill_frac: float,
                seed: int, adopt_budget: float) -> dict:
    from ompi_tpu.testing.simfleet import SimFleet

    n_ranks = n_daemons * ranks_per_daemon
    row: dict = {
        "bench": "fleet", "n_daemons": n_daemons, "n_ranks": n_ranks,
        "kill_frac": kill_frac, "seed": seed, "ok": True,
    }
    fleet = SimFleet(n_daemons=n_daemons, n_ranks=n_ranks, seed=seed,
                     hb_period=0.5, hb_timeout=3.0,
                     agg_budget_rows=max(64, n_ranks // 2))
    t0 = time.monotonic()
    fleet.start(timeout=max(60.0, n_daemons))
    row["boot_s"] = round(time.monotonic() - t0, 4)
    try:
        victims = fleet.rack(max(1, int(n_daemons * kill_frac)))
        row["killed_daemons"] = len(victims)
        fleet.rack_kill(victims)
        adopt_s = fleet.wait_adopted(timeout=adopt_budget)
        row["adopt_s"] = None if adopt_s is None else round(adopt_s, 4)
        st = fleet.stats()
        row["reparent_epochs"] = st["reparent_epochs_total"]
        row["reparent_orphans"] = st["reparent_orphans_total"]
        row["reparent_frames"] = st["reparent_frames_total"]
        row["false_positive_ranks"] = len(
            fleet.false_positive_rank_deaths())
        row["self_failed_daemons"] = len(fleet.self_failed())
        row["hb_ticks"] = st["hb_ticks_total"]
        row["hb_scanned"] = st["hb_scanned_total"]

        t0 = time.monotonic()
        rows, seen = fleet.collect_doctor(timeout=15.0)
        row["doctor_s"] = round(time.monotonic() - t0, 4)
        row["doctor_rows"] = len(rows)
        row["doctor_replied"] = len(seen)

        fleet.metrics_storm(full=True)
        time.sleep(1.0)
        st = fleet.stats()
        row["agg_merges"] = st["agg_merges_total"]
        row["agg_merge_ns"] = st["agg_merge_ns_total"]
        row["agg_sheds"] = st["agg_sheds_total"]
        row["agg_shed_rows"] = st["agg_shed_rows_total"]
        row["live_daemons"] = st["live_daemons"]
    finally:
        fleet.stop()

    # the CI invariants (reported per row; --assert folds them into rc)
    failures = []
    if row["adopt_s"] is None:
        failures.append(f"adoption did not converge in {adopt_budget}s")
    if row["false_positive_ranks"]:
        failures.append(
            f"{row['false_positive_ranks']} healthy rank(s) declared "
            f"dead")
    if row["self_failed_daemons"]:
        failures.append(
            f"{row['self_failed_daemons']} surviving daemon(s) gave up")
    if row["reparent_epochs"] != 1:
        failures.append(
            f"{row['reparent_epochs']} reparent epochs for ONE "
            f"correlated loss (want 1 batched round)")
    if row["reparent_frames"] > 2 * max(1, row["reparent_orphans"]):
        failures.append(
            f"{row['reparent_frames']} reparent frames for "
            f"{row['reparent_orphans']} orphans (bound: 2x)")
    if row["doctor_replied"] < row["live_daemons"]:
        failures.append(
            f"doctor: {row['doctor_replied']}/{row['live_daemons']} "
            f"daemons replied")
    row["ok"] = not failures
    row["failures"] = failures
    return row


def main() -> None:
    ap = argparse.ArgumentParser(
        description="simulated-fleet control-plane scale bench")
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke sizing: fewer/smaller worlds")
    ap.add_argument("--worlds", default="",
                    help="comma list of daemon counts (overrides sizing)")
    ap.add_argument("--ranks-per-daemon", type=int, default=10)
    ap.add_argument("--kill-frac", type=float, default=0.16,
                    help="fraction of daemons killed in one tick")
    ap.add_argument("--adopt-budget", type=float, default=30.0,
                    help="seconds full adoption must land within")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--assert", dest="strict", action="store_true",
                    help="nonzero exit when any invariant fails")
    ap.add_argument("--guard", action="store_true",
                    help="preflight: refuse to bench when hours-old "
                    "PPID-1 orphaned ompi_tpu processes poison the box")
    ap.add_argument("--guard-kill", action="store_true",
                    help="like --guard but SIGKILL the orphans and "
                    "proceed")
    ap.add_argument("--out", default=_OUT)
    args = ap.parse_args()

    if args.guard or args.guard_kill:
        from tools import killorphans

        if not killorphans.preflight("fleet_bench",
                                     kill=args.guard_kill):
            sys.exit(2)

    if args.worlds:
        worlds = [int(w) for w in args.worlds.split(",") if w.strip()]
    elif args.quick:
        worlds = [25, 100]
    else:
        worlds = [25, 50, 100, 200]

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    stamp = time.time()
    rows = []
    ok = True
    for n in worlds:
        row = bench_world(n, args.ranks_per_daemon, args.kill_frac,
                          args.seed, args.adopt_budget)
        row["ts"] = stamp
        rows.append(row)
        ok = ok and row["ok"]
        status = "ok" if row["ok"] else "FAIL " + "; ".join(
            row["failures"])
        print(f"[fleet_bench] {n} daemons / {row['n_ranks']} ranks: "
              f"boot {row['boot_s']}s, adopt {row['adopt_s']}s, "
              f"{row['reparent_frames']} frames / "
              f"{row['reparent_orphans']} orphans, doctor "
              f"{row['doctor_rows']} rows — {status}")

    with open(args.out, "a") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    print(f"[fleet_bench] {len(rows)} row(s) -> {args.out}")
    if args.strict and not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
