#!/usr/bin/env python
"""Flagship MFU config sweep on the live backend.

Runs each (batch, ce_chunk, remat, attention) config in a fresh subprocess
with its own wall-clock budget — a hung compile (the round-4 tunnel failure
mode: remote compile helper stalling >500s) costs one config, not the sweep.
Appends one JSON line per config to MFU_SWEEP.jsonl.

Usage:  python tools/mfu_sweep.py            # full grid
        python tools/mfu_sweep.py --quick    # the two head-to-head configs
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "MFU_SWEEP.jsonl")

CHILD = r"""
import json, sys, time
import numpy as np
cfg = json.loads(sys.argv[1])
t0 = time.time()
import jax
sys.path.insert(0, {repo!r})
from ompi_tpu.models.transformer import TransformerConfig
from ompi_tpu.parallel.mesh import make_mesh
from bench import _time_train_loop, _peak_flops

kind = jax.devices()[0].platform
mesh = make_mesh({{"dp": 1, "sp": 1, "tp": 1}}, devices=jax.devices()[:1])
base = dict(vocab=32_000, d_model=2048, n_heads=16, n_layers=8,
            d_ff=8192, seq=1024)
batch = cfg.pop("batch")
chain = cfg.pop("chain", 8)
outer = cfg.pop("outer", 2)
base.update(cfg)
rng = np.random.default_rng(0)
tokens = rng.integers(0, base["vocab"], size=(batch, base["seq"])).astype(np.int32)
t_dev = time.time()
dt, n_params, loss = _time_train_loop(
    TransformerConfig(**base, compute_dtype="bfloat16"), mesh, tokens,
    chain, outer)
n_tokens = tokens.size
fpt = 6 * n_params + 12 * base["n_layers"] * base["d_model"] * base["seq"]
peak = _peak_flops(kind)
mfu = (fpt * n_tokens / dt / peak) if peak else 0.0
print("RESULT " + json.dumps({{
    "batch": batch, **{{k: v for k, v in cfg.items()}},
    "backend": kind, "mfu_pct": round(mfu * 100, 2),
    "step_ms": round(dt * 1e3, 2), "tokens_per_s": round(n_tokens / dt, 1),
    "loss": round(float(loss), 4), "params": n_params,
    "import_s": round(t_dev - t0, 1), "wall_s": round(time.time() - t0, 1),
}}))
""".format(repo=REPO)

GRID = [
    # (label, config, per-config budget seconds)
    ("b16-chunk128-dots", {"batch": 16, "ce_chunk": 128, "remat": "dots",
                           "attention": "flash"}, 1500),
    ("b16-chunk128-noremat", {"batch": 16, "ce_chunk": 128, "remat": None,
                              "attention": "flash"}, 1500),
    ("b32-chunk128-dots", {"batch": 32, "ce_chunk": 128, "remat": "dots",
                           "attention": "flash", "chain": 4}, 1800),
    ("b32-chunk128-noremat", {"batch": 32, "ce_chunk": 128, "remat": None,
                              "attention": "flash", "chain": 4}, 1800),
    ("b16-full-dots", {"batch": 16, "ce_chunk": 0, "remat": "dots",
                       "attention": "flash"}, 1500),  # r4 preflight repro
]

QUICK = [GRID[0], GRID[2]]


def run_one(label: str, cfg: dict, budget: float) -> dict:
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", CHILD, json.dumps(cfg)],
            capture_output=True, text=True, timeout=budget, cwd=REPO)
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT "):
                rec = json.loads(line[len("RESULT "):])
                rec["label"] = label
                return rec
        return {"label": label, "error": "no result",
                "rc": proc.returncode,
                "stderr_tail": proc.stderr[-800:],
                "wall_s": round(time.time() - t0, 1)}
    except subprocess.TimeoutExpired:
        return {"label": label, "error": f"timeout after {budget}s",
                "wall_s": round(time.time() - t0, 1)}


def main() -> None:
    grid = QUICK if "--quick" in sys.argv else GRID
    for label, cfg, budget in grid:
        print(f"[sweep] {label} (budget {budget}s) ...", flush=True)
        rec = run_one(label, dict(cfg), budget)
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"[sweep] {label}: {json.dumps(rec)}", flush=True)


if __name__ == "__main__":
    main()
