#!/usr/bin/env python
"""Flagship MFU config sweep on the live backend.

Runs each (batch, ce_chunk, remat, attention) config in a fresh subprocess
with its own wall-clock budget — a hung compile (the round-4 tunnel failure
mode: remote compile helper stalling >500s) costs one config, not the sweep.
Appends one JSON line per config to MFU_SWEEP.jsonl.

Usage:  python tools/mfu_sweep.py            # full grid
        python tools/mfu_sweep.py --quick    # the two head-to-head configs
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# children inherit the shared persistent XLA compile cache (the tunnel's
# remote compile helper stalls; a disk hit skips it entirely) — one
# resolution of the cache dir, owned by bench._enable_compile_cache
sys.path.insert(0, REPO)
from bench import _enable_compile_cache  # noqa: E402

_enable_compile_cache()
OUT = os.path.join(REPO, "MFU_SWEEP.jsonl")

CHILD = r"""
import json, sys, time
import numpy as np
cfg = json.loads(sys.argv[1])
t0 = time.time()
import jax
sys.path.insert(0, {repo!r})
from ompi_tpu.models.transformer import TransformerConfig
from ompi_tpu.parallel.mesh import make_mesh
from bench import _time_train_loop, _peak_flops

kind = jax.devices()[0].device_kind  # "TPU v5 lite" (platform would
# read "tpu"/"cpu" and never match the per-generation peak table)
mesh = make_mesh({{"dp": 1, "sp": 1, "tp": 1}}, devices=jax.devices()[:1])
base = dict(vocab=32_000, d_model=2048, n_heads=16, n_layers=8,
            d_ff=8192, seq=1024)
batch = cfg.pop("batch")
chain = cfg.pop("chain", 8)
outer = cfg.pop("outer", 2)
mca = cfg.pop("_mca", None)
if mca:
    import ompi_tpu.ops.flash_attention  # registers the ops_* vars
    from ompi_tpu.core.config import var_registry
    for k, v in mca.items():
        var_registry.set(k, v)
base.update(cfg)
rng = np.random.default_rng(0)
tokens = rng.integers(0, base["vocab"], size=(batch, base["seq"])).astype(np.int32)
t_dev = time.time()
dt, n_params, loss = _time_train_loop(
    TransformerConfig(**base, compute_dtype="bfloat16"), mesh, tokens,
    chain, outer)
n_tokens = tokens.size
fpt = 6 * n_params + 12 * base["n_layers"] * base["d_model"] * base["seq"]
peak = _peak_flops(kind)
mfu = (fpt * n_tokens / dt / peak) if peak else 0.0
print("RESULT " + json.dumps({{
    "batch": batch, **{{k: v for k, v in cfg.items()}},
    "backend": kind, "mfu_pct": round(mfu * 100, 2),
    "step_ms": round(dt * 1e3, 2), "tokens_per_s": round(n_tokens / dt, 1),
    "loss": round(float(loss), 4), "params": n_params,
    "import_s": round(t_dev - t0, 1), "wall_s": round(time.time() - t0, 1),
}}))
""".format(repo=REPO)

MATMUL_PEAK = r"""
import json, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
# Per-dispatch tunnel round-trip: time a trivial program end to end.
# On the axon tunnel this measured ~780ms (!) — every number taken from
# a python-side dispatch loop is dominated by it, so the matmul loop
# below runs INSIDE one compiled program (fori_loop) and the train-loop
# rows chain steps in-jit (bench.py's chain) for the same reason.
tiny = jax.jit(lambda a: a + 1.0)
z = jax.device_put(np.zeros((8, 128), np.float32))
z = tiny(z); jax.block_until_ready(z)
t0 = time.perf_counter(); z = tiny(z); _ = float(z[0, 0])
dispatch_ms = (time.perf_counter() - t0) * 1e3
# two-point method: time the SAME program shape at two in-jit iteration
# counts; the slope cancels the (large, noisy) per-dispatch round trip
n, lo, hi = 8192, 8, 72
x = jax.device_put(np.random.default_rng(0).standard_normal(
    (n, n)).astype(jnp.bfloat16))


def make(iters):
    f = jax.jit(lambda a: lax.fori_loop(
        0, iters, lambda i, y: (y @ y) * jnp.bfloat16(1e-4), a))
    y = f(x)
    jax.block_until_ready(y)  # compile + warm

    def timed():
        t0 = time.perf_counter()
        out = f(x)
        _ = float(jnp.float32(out[0, 0]))
        return time.perf_counter() - t0

    return min(timed() for _ in range(3))


t_lo, t_hi = make(lo), make(hi)
dt = (t_hi - t_lo) / (hi - lo)
tf = 2 * n ** 3 / dt / 1e12
import sys as _s
_s.path.insert(0, {repo!r})
from bench import _peak_flops
peak = _peak_flops(jax.devices()[0].device_kind)
print("RESULT " + json.dumps({{
    "n": n, "iters": [lo, hi], "ms": round(dt * 1e3, 3),
    "dispatch_rt_ms": round(dispatch_ms, 1),
    "wall_lo_s": round(t_lo, 3), "wall_hi_s": round(t_hi, 3),
    "tflops": round(tf, 1),
    "pct_of_peak": round(tf / peak * 100, 1) if peak else None,
    "peak_tflops": round(peak / 1e12) if peak else None,
    "backend": jax.devices()[0].platform}}))
""".format(repo=REPO)

GRID = [
    # (label, config, per-config budget seconds).  "matmul_peak" is the
    # calibration row: fraction of the 197TF bf16 peak a plain 8k matmul
    # hits on this tunnel — the realistic ceiling for every MFU row.
    ("matmul_peak", None, 600),
    ("b16-chunk128-dots", {"batch": 16, "ce_chunk": 128, "remat": "dots",
                           "attention": "flash"}, 1500),
    ("b16-chunk128-noremat", {"batch": 16, "ce_chunk": 128, "remat": None,
                              "attention": "flash"}, 1500),
    # plain XLA dot-product attention instead of the pallas flash kernel
    # (attention is ~7% of model FLOPs; a slow custom kernel could still
    # dominate wall time)
    ("b16-chunk128-xla", {"batch": 16, "ce_chunk": 128,
                          "remat": "dots", "attention": "xla"}, 1500),
    ("b16-noremat-xla", {"batch": 16, "ce_chunk": 128, "remat": None,
                         "attention": "xla"}, 1500),
    # CE chunk size: fewer scan trips, bigger unembed matmuls
    ("b16-chunk256-dots", {"batch": 16, "ce_chunk": 256, "remat": "dots",
                           "attention": "flash"}, 1500),
    ("b16-chunk512-dots", {"batch": 16, "ce_chunk": 512, "remat": "dots",
                           "attention": "flash"}, 1500),
    ("b32-chunk128-dots", {"batch": 32, "ce_chunk": 128, "remat": "dots",
                           "attention": "flash", "chain": 4, "outer": 1},
     1800),
    ("b32-chunk128-noremat", {"batch": 32, "ce_chunk": 128, "remat": None,
                              "attention": "flash", "chain": 4, "outer": 1},
     1800),
    ("b16-full-dots", {"batch": 16, "ce_chunk": 0, "remat": "dots",
                       "attention": "flash"}, 1500),  # r4 preflight repro
    # pallas BACKWARD kernels too (opt-in flag; fwd-only kernel's bwd
    # otherwise recomputes O(T²) scores through XLA)
    ("b16-chunk128-dots-pbwd", {"batch": 16, "ce_chunk": 128,
                                "remat": "dots", "attention": "flash",
                                "_mca": {"ops_flash_bwd_kernel": 1}}, 1800),
    # long chain amortizes the ~780ms tunnel dispatch round-trip (the
    # matmul_peak row measures it) — the honest steady-state number
    ("b16-chunk128-dots-chain32", {"batch": 16, "ce_chunk": 128,
                                   "remat": "dots", "attention": "flash",
                                   "chain": 32, "outer": 1}, 1800),
    # combos on the measured winner (xla local attention beat the pallas
    # kernel 909 vs 1014 ms/step at b16)
    ("b16-xla-ce512", {"batch": 16, "ce_chunk": 512, "remat": "dots",
                       "attention": "xla"}, 1500),
    ("b16-xla-chain32", {"batch": 16, "ce_chunk": 128, "remat": "dots",
                         "attention": "xla", "chain": 32, "outer": 1},
     1800),
    ("b32-xla", {"batch": 32, "ce_chunk": 128, "remat": "dots",
                 "attention": "xla", "chain": 4, "outer": 1}, 1800),
    ("b16-flash-ce256-chain32", {"batch": 16, "ce_chunk": 256,
                                 "remat": "dots", "attention": "flash",
                                 "chain": 32, "outer": 1}, 1800),
    ("b16-xla-ce256-chain32", {"batch": 16, "ce_chunk": 256,
                               "remat": "dots", "attention": "xla",
                               "chain": 32, "outer": 1}, 1800),
    # ---- round-4 continuation: push past 34.6% toward the 40% bar ----
    # bigger batch between the 16 winner and the 32 OOM
    ("b24-xla-ce256-chain24", {"batch": 24, "ce_chunk": 256,
                               "remat": "dots", "attention": "xla",
                               "chain": 24, "outer": 1}, 1800),
    # b32 fits if every layer activation is rematerialized (full remat
    # costs ~33% more FLOPs on paper but bigger matmuls may win it back)
    ("b32-xla-full-chain16", {"batch": 32, "ce_chunk": 256,
                              "remat": "full", "attention": "xla",
                              "chain": 16, "outer": 1}, 1800),
    ("b32-flash-full-chain16", {"batch": 32, "ce_chunk": 256,
                                "remat": "full", "attention": "flash",
                                "chain": 16, "outer": 1}, 1800),
    # longer chain: dispatch RT (~1.5s) over 32 steps is still ~6% of
    # wall at 723ms/step; 64 halves it
    ("b16-xla-ce256-chain64", {"batch": 16, "ce_chunk": 256,
                               "remat": "dots", "attention": "xla",
                               "chain": 64, "outer": 1}, 2400),
    # bf16 first moment frees ~0.9 GiB — the cheap path to batch 32
    # with the fast "dots" remat (full remat pays ~33% extra FLOPs)
    ("b32-xla-mubf16-chain16", {"batch": 32, "ce_chunk": 256,
                                "remat": "dots", "attention": "xla",
                                "adam_mu_dtype": "bfloat16",
                                "chain": 16, "outer": 1}, 1800),
    ("b24-xla-mubf16-chain24", {"batch": 24, "ce_chunk": 256,
                                "remat": "dots", "attention": "xla",
                                "adam_mu_dtype": "bfloat16",
                                "chain": 24, "outer": 1}, 1800),
    # bf16 param storage + f32 master (param_dtype): HBM-neutral on one
    # chip (the master cancels the savings) — this row measures the
    # halved param-read bandwidth per step, not a memory win
    ("b16-xla-pbf16-chain32", {"batch": 16, "ce_chunk": 256,
                               "remat": "dots", "attention": "xla",
                               "param_dtype": "bfloat16",
                               "adam_mu_dtype": "bfloat16",
                               "chain": 32, "outer": 1}, 1800),
    # effective batch 32 via 2 in-jit microbatches: b16's activation
    # peak, one optimizer pass per 32-sample step
    ("b32-accum2-xla-chain16", {"batch": 32, "grad_accum": 2,
                                "ce_chunk": 256, "remat": "dots",
                                "attention": "xla",
                                "chain": 16, "outer": 1}, 1800),
    # flash kernel block-size tuning at seq 1024 (the kernel lost to
    # XLA attention at the 128x128 default; bigger k-streaming blocks
    # raise arithmetic intensity per grid cell)
    ("b16-flash-bq256", {"batch": 16, "ce_chunk": 256, "remat": "dots",
                         "attention": "flash", "chain": 16, "outer": 1,
                         "_mca": {"ops_flash_block_q": 256,
                                  "ops_flash_block_k": 256}}, 1800),
    ("b16-flash-bk512", {"batch": 16, "ce_chunk": 256, "remat": "dots",
                         "attention": "flash", "chain": 16, "outer": 1,
                         "_mca": {"ops_flash_block_q": 128,
                                  "ops_flash_block_k": 512}}, 1800),
    # longer sequence at constant tokens/step: attention FLOPs per token
    # double (12·L·D·S) while weight-read overhead stays flat, so MFU
    # usually rises IF the attention backward fits; flash may retake the
    # lead from XLA attention at 2048 (it lost at 1024)
    ("b8-s2048-xla-chain16", {"batch": 8, "seq": 2048, "ce_chunk": 256,
                              "remat": "dots", "attention": "xla",
                              "chain": 16, "outer": 1}, 1800),
    ("b8-s2048-flash-chain16", {"batch": 8, "seq": 2048, "ce_chunk": 256,
                                "remat": "dots", "attention": "flash",
                                "chain": 16, "outer": 1}, 1800),
    ("b4-s4096-flash-chain16", {"batch": 4, "seq": 4096, "ce_chunk": 256,
                                "remat": "dots", "attention": "flash",
                                "chain": 16, "outer": 1}, 1800),
]

_QUICK_LABELS = ["matmul_peak", "b16-chunk128-dots", "b32-chunk128-dots"]
QUICK = [row for row in GRID if row[0] in _QUICK_LABELS]


def run_one(label: str, cfg: dict | None, budget: float) -> dict:
    t0 = time.time()
    try:
        if cfg is None:  # calibration row
            argv = [sys.executable, "-c", MATMUL_PEAK]
        else:
            argv = [sys.executable, "-c", CHILD, json.dumps(cfg)]
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=budget, cwd=REPO)
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT "):
                rec = json.loads(line[len("RESULT "):])
                rec["label"] = label
                return rec
        return {"label": label, "error": "no result",
                "rc": proc.returncode,
                "stderr_tail": proc.stderr[-800:],
                "wall_s": round(time.time() - t0, 1)}
    except subprocess.TimeoutExpired:
        return {"label": label, "error": f"timeout after {budget}s",
                "wall_s": round(time.time() - t0, 1)}


def main() -> None:
    names = [a for a in sys.argv[1:] if not a.startswith("-")]
    if "--quick" in sys.argv:
        grid = QUICK
    elif names:
        by = {label: (label, cfg, budget) for label, cfg, budget in GRID}
        unknown = [n for n in names if n not in by]
        if unknown:
            sys.exit(f"unknown row(s) {unknown}; known: {sorted(by)}")
        grid = [by[n] for n in names]
    else:
        grid = GRID
    for label, cfg, budget in grid:
        print(f"[sweep] {label} (budget {budget}s) ...", flush=True)
        rec = run_one(label, dict(cfg) if cfg else None, budget)
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())
        with open(OUT, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(f"[sweep] {label}: {json.dumps(rec)}", flush=True)


if __name__ == "__main__":
    main()
