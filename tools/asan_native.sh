#!/usr/bin/env bash
# Sanitizer build + test of the native layer (convertor.cpp, fastdss.c,
# arena.c, net.c).
#
# Compiles the native sources with -fsanitize=address,undefined to the
# exact hash-named paths the lazy loader expects, then runs the
# convertor/pack/dss/arena test subset with the sanitizer runtimes
# preloaded (python itself is not ASAN-built, so libasan/libubsan must
# come in via LD_PRELOAD).  Any heap overflow / UB in the C walks fails
# the run.  The sanitized .so files are deleted afterwards: they only
# load under the preload, and leaving them in the hash cache would make
# a normal run silently fall back to numpy.
#
# Usage: tools/asan_native.sh  (from the repo root; CI's asan-native job)
set -euo pipefail

CXX=${CXX:-g++}
CC=${CC:-gcc}
SAN="-fsanitize=address,undefined -fno-sanitize-recover=all -O1 -g"

# hash-named destinations, straight from the loader
eval "$(python - <<'EOF'
import sysconfig
from ompi_tpu import _native as n
soabi = sysconfig.get_config_var("SOABI") or "abi-unknown"
print(f"CONV_SO={n._so_path()}")
print(f"FASTDSS_SO={n._hash_name(n._FASTDSS_SRC, f'_fastdss-{soabi}')}")
print(f"ARENA_SO={n._hash_name(n._ARENA_SRC, '_arena')}")
print(f"NET_SO={n._hash_name(n._NET_SRC, '_net')}")
print(f"PYINC={sysconfig.get_paths()['include']}")
EOF
)"

cleanup() { rm -f "$CONV_SO" "$FASTDSS_SO" "$ARENA_SO" "$NET_SO"; }
trap cleanup EXIT

echo "== sanitized build: convertor.cpp -> $CONV_SO"
$CXX $SAN -shared -fPIC -o "$CONV_SO" ompi_tpu/_native/convertor.cpp
echo "== sanitized build: fastdss.c -> $FASTDSS_SO"
$CC $SAN -shared -fPIC -I"$PYINC" -o "$FASTDSS_SO" \
    ompi_tpu/_native/fastdss.c
echo "== sanitized build: arena.c -> $ARENA_SO"
$CC $SAN -shared -fPIC -o "$ARENA_SO" ompi_tpu/_native/arena.c
echo "== sanitized build: net.c -> $NET_SO"
$CC $SAN -shared -fPIC -o "$NET_SO" ompi_tpu/_native/net.c

LIBASAN=$($CXX -print-file-name=libasan.so)
LIBUBSAN=$($CXX -print-file-name=libubsan.so)

# leak detection off: CPython "leaks" interned objects by design, and
# the interceptors see every allocation the interpreter ever makes —
# the signal here is overflow/UB in OUR walks, not interpreter noise
export ASAN_OPTIONS="detect_leaks=0:abort_on_error=1"
export UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1"
export LD_PRELOAD="$LIBASAN:$LIBUBSAN"

echo "== native layer self-check under ASan/UBSan"
python - <<'EOF'
from ompi_tpu import _native
lib = _native.lib()
assert lib is not None, "sanitized convertor failed to load"
assert lib.ompi_tpu_native_abi() == _native._ABI
fd = _native.fastdss()
assert fd is not None, "sanitized fastdss failed to load"
ar = _native.arena()
assert ar is not None, "sanitized arena executor failed to load"
assert ar.ompi_tpu_arena_abi() == _native._ARENA_ABI
nt = _native.net()
assert nt is not None, "sanitized net plane failed to load"
assert nt.ompi_tpu_net_abi() == _native._NET_ABI
print("sanitized native layer loaded, ABI", _native._ABI,
      "arena ABI", _native._ARENA_ABI, "net ABI", _native._NET_ABI)
EOF

echo "== convertor/pack/dss/arena/net tests under ASan/UBSan"
# test_native_arena drives every arena entry point (waits, publishes,
# strided walks, every fold width, ring parks, dense copy_blocks
# gathers); test_coll_shm runs the full collective protocols —
# including the arena dense-exchange plane (alltoall/v/w,
# reduce_scatter, scan) — over the sanitized executor;
# test_native_net drives the tcp submission rings, send3/writev drains,
# parked poller and zero-copy landing over real loopback sockets
env JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
    tests/core/test_dss.py \
    tests/mpi/test_datatype.py \
    tests/mpi/test_datatype_ext.py \
    tests/mpi/test_datatype_fuzz.py \
    tests/mpi/test_pack_plan.py \
    tests/mpi/test_native_arena.py \
    tests/mpi/test_native_net.py \
    tests/mpi/test_coll_shm.py \
    tests/mpi/test_coll_dense.py
echo "== ASan/UBSan native run clean"
