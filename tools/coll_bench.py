"""On-node collective microbench: coll/shm arena vs coll/host p2p.

Latency-vs-size for allreduce / bcast / barrier on an in-process
multi-rank world (the tests/mpi harness topology: one PML per rank,
real matching, real shm-BTL rings for the host path — the same rig the
58 µs/hop scheduler-floor number was measured on), run twice per
config: once with the coll/shm arena enabled and once forced to
coll/host (``coll_shm_enable 0``).  The per-op number is wall time of
a synchronized loop divided by iterations, best of ``--reps`` runs —
the two-point/best-of discipline bench.py uses, collective form.

Rows append to ``COLL_BENCH.jsonl`` next to the repo root (the
PACK_BENCH.jsonl convention — append-only, one JSON object per line)
so the shm-vs-host crossover table in PERF.md stays reproducible.

Run: ``python tools/coll_bench.py [--quick] [--ranks 4]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ompi_tpu.core.config import var_registry  # noqa: E402
from ompi_tpu.mpi.coll import shm as _shm  # noqa: E402,F401 — register vars
from ompi_tpu.mpi.comm import Communicator  # noqa: E402
from ompi_tpu.mpi.group import Group  # noqa: E402
from ompi_tpu.mpi.pml import PmlOb1  # noqa: E402

_OUT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "COLL_BENCH.jsonl")


def _hist_percentiles(before: dict, after: dict, base: str,
                      label: str = "") -> tuple[float, float]:
    """(p50 µs, p99 µs) of one histogram family's delta between two
    ``trace.hists_snapshot()`` snapshots, summed over the series whose
    key carries ``label`` (e.g. ``slot="allreduce"``) — the per-size-row
    tail the mean alone hides."""
    from ompi_tpu.mpi import trace

    counts = [0] * trace.HIST_NBUCKETS
    for key, vec in after.items():
        if not (key == base or key.startswith(base + "{")):
            continue
        if label and label not in key:
            continue
        b = before.get(key)
        for i in range(trace.HIST_NBUCKETS):
            counts[i] += vec[i] - (b[i] if b else 0)
    return (round(trace.hist_quantile_ns(counts, 0.50) / 1e3, 1),
            round(trace.hist_quantile_ns(counts, 0.99) / 1e3, 1))


def _run_world(n: int, fn, timeout: float = 300.0) -> list:
    """In-process n-rank world (tests/mpi/harness.run_ranks, inlined so
    the tool has no test-tree import)."""
    pmls = [PmlOb1(r) for r in range(n)]
    addrs = {r: p.address for r, p in enumerate(pmls)}
    for p in pmls:
        p.set_peers(addrs)
    comms = [Communicator(Group(range(n)), cid=0, pml=pmls[r],
                          my_world_rank=r, name=f"bench{n}")
             for r in range(n)]
    results: list = [None] * n
    errors: list = []

    def runner(rank: int) -> None:
        try:
            results[rank] = fn(comms[rank])
        except BaseException as e:  # noqa: BLE001
            errors.append((rank, e))

    threads = [threading.Thread(target=runner, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    try:
        if any(t.is_alive() for t in threads):
            raise TimeoutError(f"bench ranks hung (errors: {errors})")
        if errors:
            raise errors[0][1]
    finally:
        if not any(t.is_alive() for t in threads):
            for p in pmls:
                p.close()
    return results


def _make_x(comm, coll: str, nbytes: int):
    """The per-rank send buffer for one size row.  ``payload_bytes``
    is the TOTAL sendbuf (for alltoall that is p blocks of nbytes/p —
    the MoE-dispatch accounting, where the row size is what one rank
    ships, not what one peer receives)."""
    if not nbytes:
        return None
    if coll == "alltoall":
        per = max(nbytes // 8 // comm.size, 1)
        return (np.arange(per * comm.size, dtype=np.float64)
                .reshape(comm.size, per) + comm.rank)
    return np.arange(max(nbytes // 8, 1), dtype=np.float64) + comm.rank


def _coll_op(comm, coll: str, x, i: int) -> None:
    if coll == "allreduce":
        comm.allreduce(x)
    elif coll == "bcast":
        # rotating root (the IMB discipline): iteration i's root
        # was a receiver in iteration i-1, so a fixed root can't
        # run ahead enqueueing asynchronous sends — the loop
        # measures per-op completion, not enqueue throughput
        root = i % comm.size
        comm.bcast(x if comm.rank == root else None, root=root)
    elif coll == "alltoall":
        comm.alltoall(x)
    elif coll == "reduce_scatter":
        comm.reduce_scatter(x)
    else:
        comm.barrier()


def _time_coll(n: int, coll: str, nbytes: int, iters: int,
               reps: int) -> float:
    """Per-op µs: synchronized loop wall time / iters, best of reps."""

    def body(comm):
        x = _make_x(comm, coll, nbytes)

        def one(i: int) -> None:
            _coll_op(comm, coll, x, i)

        best = float("inf")
        comm.barrier()                       # warm transports + arena
        one(0)
        for _ in range(reps):
            comm.barrier()
            t0 = time.perf_counter()
            for i in range(iters):
                one(i)
            best = min(best, time.perf_counter() - t0)
        return best / iters * 1e6

    # the slowest rank's best defines the collective's latency
    return max(_run_world(n, body))


def _time_coll_pair(n: int, coll: str, nbytes: int, iters: int,
                    reps: int) -> tuple[float, float, str]:
    """(persistent µs, one-shot µs, provider): BOTH modes timed in the
    same rank world, alternating per rep, so they share scheduling
    fate — on an oversubscribed box the rank threads phase-lock into
    per-run patterns that would otherwise dominate a between-run
    comparison.  Persistent = Start/wait over ONE bound plan (bind
    outside the timed loop); one-shot = the dispatch path, fixed root
    0 both sides (the bound plan pins one root, and the one-shot
    arena bcast root waits all readers per op anyway)."""
    elems = max(nbytes // 8, 1) if nbytes else 0

    def body(comm):
        if nbytes:
            x = np.arange(elems, dtype=np.float64) + comm.rank
        if coll == "allreduce":
            req = comm.allreduce_init(x)
        elif coll == "bcast":
            req = comm.bcast_init(
                x if comm.rank == 0 else np.empty_like(x), root=0)
        else:
            req = comm.barrier_init()

        def one_persistent() -> None:
            req.start()
            req.wait()

        def one_dispatch() -> None:
            if coll == "allreduce":
                comm.allreduce(x)
            elif coll == "bcast":
                comm.bcast(x if comm.rank == 0 else None, root=0)
            else:
                comm.barrier()

        comm.barrier()                       # warm transports + slots
        one_persistent()
        one_dispatch()
        best_p = best_o = float("inf")
        for _ in range(reps):
            for fn, which in ((one_persistent, "p"),
                              (one_dispatch, "o")):
                comm.barrier()
                t0 = time.perf_counter()
                for _i in range(iters):
                    fn()
                dt = time.perf_counter() - t0
                if which == "p":
                    best_p = min(best_p, dt)
                else:
                    best_o = min(best_o, dt)
        return best_p / iters * 1e6, best_o / iters * 1e6, req.provider

    results = _run_world(n, body)
    return (max(r[0] for r in results), max(r[1] for r in results),
            results[0][2])


def bench_persistent_config(n: int, coll: str, nbytes: int, iters: int,
                            reps: int, quick: bool) -> list[dict]:
    """One size row pair: bound-plan Start steady state vs per-op
    dispatch (fixed root both sides), plus the bind/start pvar
    accounting the acceptance gate reads."""
    from ompi_tpu.mpi import trace

    b0 = trace.counters["coll_persistent_binds_total"]
    s0 = trace.counters["coll_persistent_starts_total"]
    h0 = trace.hists_snapshot()
    p_us, o_us, provider = _time_coll_pair(n, coll, nbytes, iters, reps)
    h1 = trace.hists_snapshot()
    # per-mode tails: persistent Starts land in coll_pstart_ns, the
    # one-shot dispatch path in coll_dispatch_ns
    pcts = {
        "persistent": _hist_percentiles(h0, h1, "coll_pstart_ns",
                                        label=f'kind="{coll}"'),
        "oneshot": _hist_percentiles(h0, h1, "coll_dispatch_ns",
                                     label=f'slot="{coll}"'),
    }
    # in-process ranks share the process counters: normalize per rank
    binds_pr = (trace.counters["coll_persistent_binds_total"] - b0) / n
    starts_pr = (trace.counters["coll_persistent_starts_total"] - s0) / n
    speedup = o_us / p_us if p_us else float("inf")
    rows = []
    for mode, us in (("persistent", p_us), ("oneshot", o_us)):
        rows.append({
            "p50_us": pcts[mode][0],
            "p99_us": pcts[mode][1],
            "bench": "coll_bench",
            "coll": coll,
            "ranks": n,
            "payload_bytes": nbytes,
            "component": provider if mode == "persistent" else "dispatch",
            "mode": mode,
            "per_op_us": round(us, 2),
            "persistent_speedup": round(speedup, 2),
            "binds_per_rank": binds_pr,
            "starts_per_rank": starts_pr,
            "iters": iters,
            "reps": reps,
            "n_cores": os.cpu_count(),
            "quick": quick,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
    print(f"{coll:>9} {nbytes:>9}B x{n}: Start {p_us:9.1f}us "
          f"(p99 {pcts['persistent'][1]:.0f})  "
          f"per-op {o_us:9.1f}us (p99 {pcts['oneshot'][1]:.0f})  "
          f"({speedup:.2f}x)  [{provider}: binds={binds_pr:.0f} "
          f"starts={starts_pr:.0f}]")
    return rows


def _time_coll_native_pair(n: int, coll: str, nbytes: int, iters: int,
                           reps: int) -> tuple[float, float]:
    """(native µs, python µs): the SAME arena path with the native
    executor on vs off, alternating per rep in the SAME rank world
    (shared fate — the methodology note from PR 10 applies doubly here
    because the native side's whole point is scheduler behavior).
    Rank 0 flips ``coll_shm_native`` between barriers; the arena reads
    it per call."""

    def body(comm):
        x = _make_x(comm, coll, nbytes)

        def one(i: int) -> None:
            _coll_op(comm, coll, x, i)

        best = {"nat": float("inf"), "py": float("inf")}
        comm.barrier()
        one(0)
        for _ in range(reps):
            for mode, native in (("nat", True), ("py", False)):
                comm.barrier()
                if comm.rank == 0:
                    var_registry.set("coll_shm_native", native)
                comm.barrier()   # everyone sees the flip before timing
                t0 = time.perf_counter()
                for i in range(iters):
                    one(i)
                best[mode] = min(best[mode],
                                 time.perf_counter() - t0)
        if comm.rank == 0:
            var_registry.set("coll_shm_native", True)
        return best["nat"] / iters * 1e6, best["py"] / iters * 1e6

    results = _run_world(n, body)
    return (max(r[0] for r in results), max(r[1] for r in results))


def bench_native_config(n: int, coll: str, nbytes: int, iters: int,
                        reps: int, quick: bool) -> list[dict]:
    """One size row pair: native arena executor vs the python arena
    path (the GIL-free data plane's acceptance comparison)."""
    nat_us, py_us = _time_coll_native_pair(n, coll, nbytes, iters, reps)
    speedup = py_us / nat_us if nat_us else float("inf")
    rows = []
    for mode, us in (("native", nat_us), ("python", py_us)):
        rows.append({
            "bench": "coll_bench",
            "coll": coll,
            "ranks": n,
            "payload_bytes": nbytes,
            "component": "shm",
            "mode": mode,
            "per_op_us": round(us, 2),
            "native_speedup": round(speedup, 2),
            "iters": iters,
            "reps": reps,
            "n_cores": os.cpu_count(),
            "quick": quick,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
    print(f"{coll:>9} {nbytes:>9}B x{n}: native {nat_us:9.1f}us  "
          f"python {py_us:9.1f}us  ({speedup:.2f}x)")
    return rows


def _time_segpar_pair(n: int, nbytes: int, iters: int,
                      reps: int) -> tuple[float, float]:
    """(segment_parallel µs, root_fold µs) for a persistent arena
    allreduce — BOTH plans bound in the same world, alternated per
    rep (shared fate)."""
    elems = max(nbytes // 8, 1)

    def body(comm):
        x = np.arange(elems, dtype=np.float64) + comm.rank
        if comm.rank == 0:
            var_registry.set("coll_shm_allreduce_algorithm",
                             "root_fold")
        comm.barrier()
        req_root = comm.allreduce_init(x)
        if comm.rank == 0:
            var_registry.set("coll_shm_allreduce_algorithm",
                             "segment_parallel")
        comm.barrier()
        req_seg = comm.allreduce_init(x)
        if comm.rank == 0:
            var_registry.set("coll_shm_allreduce_algorithm", "")
        assert req_root.algorithm == "root_fold", req_root.algorithm
        assert req_seg.algorithm == "segment_parallel", req_seg.algorithm
        best = {"root": float("inf"), "seg": float("inf")}
        for req in (req_root, req_seg):
            req.start()
            req.wait()
        for _ in range(reps):
            for mode, req in (("root", req_root), ("seg", req_seg)):
                comm.barrier()
                t0 = time.perf_counter()
                for _i in range(iters):
                    req.start()
                    req.wait()
                best[mode] = min(best[mode],
                                 time.perf_counter() - t0)
        req_root.free()
        req_seg.free()
        return best["seg"] / iters * 1e6, best["root"] / iters * 1e6

    results = _run_world(n, body)
    return (max(r[0] for r in results), max(r[1] for r in results))


def bench_segpar_config(n: int, nbytes: int, iters: int, reps: int,
                        quick: bool) -> list[dict]:
    """One size row pair: cooperative segment-parallel allreduce vs
    the single-rank root fold over bound persistent plans."""
    seg_us, root_us = _time_segpar_pair(n, nbytes, iters, reps)
    speedup = root_us / seg_us if seg_us else float("inf")
    rows = []
    for mode, us in (("segment_parallel", seg_us),
                     ("root_fold", root_us)):
        rows.append({
            "bench": "coll_bench",
            "coll": "allreduce",
            "ranks": n,
            "payload_bytes": nbytes,
            "component": "shm-persistent",
            "mode": mode,
            "per_op_us": round(us, 2),
            "segpar_speedup": round(speedup, 2),
            "iters": iters,
            "reps": reps,
            "n_cores": os.cpu_count(),
            "quick": quick,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
    print(f"allreduce {nbytes:>9}B x{n}: segpar {seg_us:9.1f}us  "
          f"root_fold {root_us:9.1f}us  ({speedup:.2f}x)")
    return rows


def _time_neighbor_pair(n: int, nbytes: int, iters: int,
                        reps: int) -> tuple[float, float]:
    """(persistent µs, one-shot µs) for a 2-D periodic halo exchange
    (neighbor_alltoall on a dims_create cart, one ``nbytes`` face per
    edge) — BOTH modes in the same rank world, alternating per rep
    (shared fate), the stencil-loop steady state the persistent
    neighbor plan exists for."""
    per = max(nbytes // 8, 1)

    def body(comm):
        from ompi_tpu.mpi import topo

        dims = topo.dims_create(n, 2)
        cart = topo.cart_create(comm, dims, periods=[True, True])
        parts = [np.arange(per, dtype=np.float64) + cart.rank
                 for _ in range(2 * cart.topo.ndims)]
        req = cart.neighbor_alltoall_init(parts)
        best = {"p": float("inf"), "o": float("inf")}
        cart.barrier()
        req.start()
        req.wait()
        cart.neighbor_alltoall(parts)
        for _ in range(reps):
            for which in ("p", "o"):
                cart.barrier()
                t0 = time.perf_counter()
                for _i in range(iters):
                    if which == "p":
                        req.start()
                        req.wait()
                    else:
                        cart.neighbor_alltoall(parts)
                best[which] = min(best[which],
                                  time.perf_counter() - t0)
        req.free()
        return best["p"] / iters * 1e6, best["o"] / iters * 1e6

    results = _run_world(n, body)
    return (max(r[0] for r in results), max(r[1] for r in results))


def bench_neighbor_config(n: int, nbytes: int, iters: int, reps: int,
                          quick: bool) -> list[dict]:
    """One halo size row pair: persistent neighbor Start vs the
    per-op neighbor_alltoall dispatch."""
    p_us, o_us = _time_neighbor_pair(n, nbytes, iters, reps)
    speedup = o_us / p_us if p_us else float("inf")
    rows = []
    for mode, us in (("persistent", p_us), ("oneshot", o_us)):
        rows.append({
            "bench": "coll_bench",
            "coll": "neighbor_alltoall",
            "ranks": n,
            "payload_bytes": nbytes,
            "component": "topo" if mode == "persistent" else "dispatch",
            "mode": mode,
            "per_op_us": round(us, 2),
            "persistent_speedup": round(speedup, 2),
            "iters": iters,
            "reps": reps,
            "n_cores": os.cpu_count(),
            "quick": quick,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
    print(f"neighbor2d {nbytes:>8}B x{n}: Start {p_us:9.1f}us  "
          f"per-op {o_us:9.1f}us  ({speedup:.2f}x)")
    return rows


def bench_config(n: int, coll: str, nbytes: int, iters: int, reps: int,
                 quick: bool) -> list[dict]:
    from ompi_tpu.mpi import trace

    rows = []
    for component, enable in (("shm", True), ("host", False)):
        var_registry.set("coll_shm_enable", enable)
        h0 = trace.hists_snapshot()
        us = _time_coll(n, coll, nbytes, iters, reps)
        # per-size tail from the dispatch histogram (the in-process
        # ranks share the process-wide series; the slot label scopes
        # the delta to THIS collective, not the sync barriers)
        p50, p99 = _hist_percentiles(
            h0, trace.hists_snapshot(), "coll_dispatch_ns",
            label=f'slot="{coll}"')
        rows.append({
            "bench": "coll_bench",
            "coll": coll,
            "ranks": n,
            "payload_bytes": nbytes,
            "component": component,
            "per_op_us": round(us, 2),
            "p50_us": p50,
            "p99_us": p99,
            "iters": iters,
            "reps": reps,
            "n_cores": os.cpu_count(),
            "quick": quick,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        })
    var_registry.set("coll_shm_enable", True)
    a, b = rows[0]["per_op_us"], rows[1]["per_op_us"]
    speedup = b / a if a else float("inf")
    for r in rows:
        r["shm_speedup"] = round(speedup, 2)
    print(f"{coll:>9} {nbytes:>9}B x{n}: shm {a:9.1f}us "
          f"(p50 {rows[0]['p50_us']:.0f} p99 {rows[0]['p99_us']:.0f})  "
          f"host {b:9.1f}us "
          f"(p50 {rows[1]['p50_us']:.0f} p99 {rows[1]['p99_us']:.0f})  "
          f"({speedup:.2f}x)")
    return rows


def main() -> None:
    ap = argparse.ArgumentParser(
        description="on-node shm-vs-host collective latency")
    ap.add_argument("--ranks", type=int, default=4)
    ap.add_argument("--quick", action="store_true",
                    help="CI smoke sizing: fewer sizes, fewer iters")
    ap.add_argument("--persistent", action="store_true",
                    help="bind-once sweep: persistent Start steady "
                    "state vs per-op dispatch (fixed root)")
    ap.add_argument("--native", action="store_true",
                    help="GIL-free data-plane sweep: arena with the "
                    "native executor vs the python arena path, plus "
                    "segment-parallel vs root-fold persistent "
                    "allreduce at >=1MiB (all shared-fate)")
    ap.add_argument("--families", default="classic",
                    help="comma list of sweep families: 'classic' "
                    "(allreduce/bcast/barrier — the default flow) "
                    "and/or 'dense' (alltoall + reduce_scatter "
                    "shm-vs-host and native-on/off, plus the 2-D "
                    "neighbor halo persistent-vs-dispatch pair)")
    ap.add_argument("--guard", action="store_true",
                    help="preflight: refuse to bench when hours-old "
                    "PPID-1 orphaned ompi_tpu processes poison the box")
    ap.add_argument("--guard-kill", action="store_true",
                    help="like --guard but SIGKILL the orphans and "
                    "proceed")
    ap.add_argument("--out", default=_OUT)
    args = ap.parse_args()

    if args.guard or args.guard_kill:
        from tools import killorphans

        if not killorphans.preflight("coll_bench",
                                     kill=args.guard_kill):
            sys.exit(2)

    if args.quick:
        sizes = [64, 8 << 10, 256 << 10]
        iters, reps = 30, 2
    else:
        sizes = [8, 64, 1 << 10, 8 << 10, 64 << 10, 256 << 10, 1 << 20]
        iters, reps = 50, 3

    families = {f.strip() for f in args.families.split(",") if f.strip()}

    if "dense" in families:
        # alltoall rows are TOTAL sendbuf bytes (p blocks of size/p);
        # the 4KiB–4MiB sweep crosses the arena slot cap on purpose —
        # above it coll/shm falls back to host and the speedup column
        # honestly flattens to ~1x (the crossover the PERF table shows)
        dense_sizes = ([8 << 10, 64 << 10] if args.quick
                       else [4 << 10, 16 << 10, 64 << 10, 256 << 10,
                             1 << 20, 4 << 20])
        rows = []
        for coll in ("alltoall", "reduce_scatter"):
            for nbytes in dense_sizes:
                it = max(5, iters // 4) if nbytes >= (256 << 10) \
                    else iters
                rows += bench_config(args.ranks, coll, nbytes, it,
                                     reps, args.quick)
        # shared-fate native on/off over the same arena route
        nat_sizes = ([16 << 10] if args.quick
                     else [16 << 10, 64 << 10, 256 << 10])
        for coll in ("alltoall", "reduce_scatter"):
            for nbytes in nat_sizes:
                rows += bench_native_config(args.ranks, coll, nbytes,
                                            iters, reps, args.quick)
        for nbytes in ([8 << 10] if args.quick
                       else [4 << 10, 64 << 10]):
            rows += bench_neighbor_config(args.ranks, nbytes, iters,
                                          reps, args.quick)
        with open(args.out, "a", encoding="utf-8") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        print(f"{len(rows)} rows -> {args.out}")
        for coll in ("alltoall", "reduce_scatter"):
            wins = sum(1 for r in rows
                       if r["coll"] == coll and r["component"] == "shm"
                       and "shm_speedup" in r
                       and r["payload_bytes"] >= (16 << 10)
                       and r["shm_speedup"] > 1.0)
            print(f"{coll}: arena beats host pairwise at {wins} "
                  f">=16KiB size(s)")
            if not args.quick and wins < 1:
                print(f"WARNING: expected an arena win >=16KiB "
                      f"for {coll}")
        if "classic" not in families:
            return

    if args.native:
        # the GIL-bound band the native plane targets, bracketed by one
        # small and one large size for the honest-crossover table
        nat_sizes = ([8 << 10, 64 << 10] if args.quick
                     else [64, 8 << 10, 16 << 10, 32 << 10, 64 << 10,
                           256 << 10])
        rows = bench_native_config(args.ranks, "barrier", 0, iters,
                                   reps, args.quick)
        for coll in ("allreduce", "bcast"):
            for nbytes in nat_sizes:
                it = max(5, iters // 4) if nbytes >= (256 << 10) \
                    else iters
                rows += bench_native_config(args.ranks, coll, nbytes,
                                            it, reps, args.quick)
        for nbytes in ([1 << 20] if args.quick
                       else [1 << 20, 2 << 20, 4 << 20]):
            rows += bench_segpar_config(args.ranks, nbytes,
                                        max(5, iters // 5), reps,
                                        args.quick)
        with open(args.out, "a", encoding="utf-8") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        print(f"{len(rows)} rows -> {args.out}")
        wins = {(r["coll"], r["payload_bytes"]) for r in rows
                if r["mode"] == "native"
                and (8 << 10) <= r["payload_bytes"] <= (64 << 10)
                and r["native_speedup"] >= 1.5}
        print(f"native >=1.5x at {len(wins)} of the 8-64KiB "
              f"size rows (acceptance wants >=3)")
        seg_wins = sum(1 for r in rows if r["mode"] == "segment_parallel"
                       and r["segpar_speedup"] > 1.0)
        print(f"segment-parallel beats root-fold at {seg_wins} "
              f">=1MiB size(s)")
        return

    if args.persistent:
        # small payloads get extra reps: both modes are measured as
        # best-of, and scheduler noise on an oversubscribed box only
        # ever ADDS latency, so more reps tightens the floor estimate
        # where the dispatch-overhead difference is smallest
        small_reps = reps * 2
        rows = bench_persistent_config(args.ranks, "barrier", 0, iters,
                                       small_reps, args.quick)
        for coll in ("allreduce", "bcast"):
            for nbytes in sizes:
                it = max(5, iters // 4) if nbytes >= (256 << 10) \
                    else iters
                rp = small_reps if nbytes <= 8192 else reps
                rows += bench_persistent_config(args.ranks, coll,
                                                nbytes, it, rp,
                                                args.quick)
        with open(args.out, "a", encoding="utf-8") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        print(f"{len(rows)} rows -> {args.out}")
        small_wins = {
            (r["coll"], r["payload_bytes"]) for r in rows
            if r["mode"] == "persistent" and r["payload_bytes"] <= 8192
            and r["persistent_speedup"] >= 2.0}
        for coll in ("allreduce", "bcast"):
            n_wins = sum(1 for c, _ in small_wins if c == coll)
            print(f"{coll}: persistent >=2x at {n_wins} small "
                  f"(<=8KiB) payload size(s)")
            if n_wins < 1:
                print(f"WARNING: expected a >=2x small-payload win "
                      f"for {coll}")
        return

    rows = bench_config(args.ranks, "barrier", 0, iters, reps, args.quick)
    for coll in ("allreduce", "bcast"):
        for nbytes in sizes:
            it = max(5, iters // 4) if nbytes >= (256 << 10) else iters
            rows += bench_config(args.ranks, coll, nbytes, it, reps,
                                 args.quick)

    with open(args.out, "a", encoding="utf-8") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    print(f"{len(rows)} rows -> {args.out}")

    wins = {(r["coll"], r["payload_bytes"]) for r in rows
            if r["component"] == "shm" and r["shm_speedup"] > 1.0}
    for coll in ("allreduce", "bcast"):
        n_wins = sum(1 for c, _ in wins if c == coll)
        print(f"{coll}: shm faster at {n_wins} payload size(s)")
        if n_wins < 2:
            print(f"WARNING: expected shm to win >=2 sizes for {coll}")


if __name__ == "__main__":
    main()
