#!/usr/bin/env python
"""Fetch a cross-rank timeline — live from a running world, or
postmortem from crash dumps.

Live mode (a DVM with ``--metrics-port`` is up and a job is running):

    python tools/timeline.py -o trace.json
    python tools/timeline.py --uri http://127.0.0.1:9301 --tail 4096

pulls ``/timeline`` — the HNP xcasts TAG_TIMELINE, every orted gathers
bounded flight-recorder tails from its live ranks, and the reply is one
merged, skew-corrected (measured clock offsets) Chrome trace with
cross-rank flow arrows.  The default --uri is read from the DVM's
``<uri>.metrics`` file, like the scrape endpoint's other clients.

Postmortem mode (the world is gone; finalize/abort dumps remain):

    python tools/timeline.py --dir $TMPDIR --jobid 7 -o trace.json
    python tools/timeline.py --dir $TMPDIR --offsets offsets.json

delegates to tools/trace_export.py's merge over the per-rank dump
files (wall-anchor or ``--offsets`` measured correction).

Either way the output loads in chrome://tracing and
https://ui.perfetto.dev.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import urllib.request

# sibling-module import (tools/ is not a package everywhere it runs —
# CI invokes these standalone from the repo root)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import trace_export  # noqa: E402


def default_metrics_uri() -> "str | None":
    """The DVM's recorded scrape address (``<uri>.metrics``), if a DVM
    is up with the observability plane armed."""
    path = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                        f"ompi_tpu-dvm-{os.getuid()}.uri.metrics")
    try:
        with open(path, encoding="utf-8") as f:
            return f.read().strip() or None
    except OSError:
        return None


def fetch_live(uri: str, tail: int, timeout: float = 30.0) -> dict:
    """One live /timeline capture from the DVM's scrape endpoint."""
    url = f"{uri.rstrip('/')}/timeline?tail={int(tail)}"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


def main(argv: "list[str] | None" = None) -> int:
    p = argparse.ArgumentParser(
        description="Fetch a merged cross-rank timeline (live /timeline "
                    "capture or postmortem dump merge).")
    p.add_argument("--uri", default=None,
                   help="DVM metrics endpoint (default: the address in "
                        "the DVM's <uri>.metrics file)")
    p.add_argument("--tail", type=int, default=2048,
                   help="per-rank recorder tail to pull (live mode)")
    p.add_argument("--dir", default=None,
                   help="postmortem: merge ompi_tpu_trace_*.json dumps "
                        "from this directory instead of a live capture")
    p.add_argument("--jobid", type=int, default=None,
                   help="with --dir: only this job's dumps")
    p.add_argument("--offsets", default=None, metavar="FILE",
                   help="with --dir: JSON map rank → measured offset ns "
                        "(see tools/trace_export.py --offsets)")
    p.add_argument("-o", "--output", default="ompi_tpu_timeline.json")
    p.add_argument("--validate", action="store_true",
                   help="also run the exporter's schema + causality "
                        "validator on the result; nonzero exit on "
                        "problems")
    args = p.parse_args(argv)

    if args.dir:
        paths = sorted(glob.glob(os.path.join(
            args.dir, trace_export.dump_glob(args.jobid))))
        if not paths:
            print("timeline: no dumps found", file=sys.stderr)
            return 2
        offsets = None
        if args.offsets:
            with open(args.offsets, encoding="utf-8") as f:
                offsets = {int(r): float(v)
                           for r, v in json.load(f).items()
                           if v is not None}
        doc = trace_export.merge(paths, offsets=offsets)
        source = f"{len(paths)} dump(s)"
    else:
        uri = args.uri or default_metrics_uri()
        if not uri:
            print("timeline: no --uri and no DVM <uri>.metrics file "
                  "found (start one with: tpurun --dvm-start "
                  "--metrics-port 0), or use --dir for postmortem "
                  "merges", file=sys.stderr)
            return 2
        try:
            doc = fetch_live(uri, args.tail)
        except OSError as e:
            print(f"timeline: cannot reach {uri}/timeline ({e})",
                  file=sys.stderr)
            return 2
        source = f"live capture from {uri}"
        other = doc.get("otherData") or {}
        if other.get("idle"):
            print("timeline: DVM is idle (no job running, no cached "
                  "capture) — nothing to plot", file=sys.stderr)
            return 3
        if other.get("stale"):
            print("timeline: no job running — serving the cached last "
                  "capture", file=sys.stderr)

    problems = trace_export.validate(doc)
    problems += trace_export.causality_problems(
        doc.get("traceEvents") or [])
    problems += (doc.get("otherData") or {}).get(
        "causality_problems") or []
    if args.validate and problems:
        for pr in problems:
            print(f"timeline: INVALID: {pr}", file=sys.stderr)
        return 1
    for pr in problems:
        print(f"timeline: WARNING: {pr}", file=sys.stderr)

    with open(args.output, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    events = doc.get("traceEvents") or []
    n_spans = sum(1 for e in events if e.get("ph") == "X")
    n_flows = sum(1 for e in events if e.get("ph") == "s")
    other = doc.get("otherData") or {}
    print(f"timeline: wrote {args.output} — {len(events)} events "
          f"({n_spans} spans, {n_flows} flow arrows) from {source}; "
          f"clock domain: {other.get('clock_domain', '?')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
