"""Cross-rank straggler report: who is everyone waiting for?

Two sources, one verdict:

- **live** (default): GET the DVM observability endpoint's ``/status``
  (the address is read from ``<dvm-uri>.metrics`` next to the control
  URI file, or passed via ``--uri``) and print each job's straggler
  panel — the same aggregate the ``/status`` scrape serves, computed
  from the latency histograms every rank pushes up the orted tree.
- **offline** (``--dir``): read the per-rank flight-recorder dumps
  (``ompi_tpu_trace_<jobid>_rank<r>.json``, written by ``--trace`` runs
  and crash dumps), pull each rank's histogram vectors out of
  ``otherData.hists``, and run the identical panel math
  (``runtime.metrics.straggler_panel``) over the whole run — the
  post-mortem path when no DVM is left to ask.

The inversion both paths share: the rank with the LOWEST share of the
job's total collective wait time is the one every other rank spent its
wait time waiting for — the last arriver barely waits.

Run: ``python tools/straggler_report.py [--uri http://…|--dir /tmp]``.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ompi_tpu.runtime.metrics import straggler_panel  # noqa: E402

_DUMP_RE = re.compile(r"ompi_tpu_trace_(\d+)_rank(\d+)\.json$")


def _default_uri() -> str:
    from ompi_tpu.runtime.dvm import default_uri_path

    path = default_uri_path() + ".metrics"
    with open(path, encoding="utf-8") as f:
        return f.read().strip()


def _print_panel(jobid, panel: dict, out=sys.stdout) -> None:
    print(f"job {jobid}  [signal: {panel['signal']}, window "
          f"{panel['window_s']:.1f}s]", file=out)
    print(f"  {'rank':>5} {'wait_ms':>12} {'publish_ms':>12} "
          f"{'wait_share':>11}", file=out)
    for rank in sorted(panel["ranks"], key=int):
        row = panel["ranks"][rank]
        mark = "  <- suspect" if (panel["suspect"] is not None
                                  and int(rank)
                                  == int(panel["suspect"])) else ""
        print(f"  {rank:>5} {row['wait_ms']:>12.3f} "
              f"{row['publish_ms']:>12.3f} {row['wait_share']:>11.4f}"
              f"{mark}", file=out)
    skew = panel["skew"]
    print(f"  max/median wait: {panel['max_wait_ms']:.3f}/"
          f"{panel['median_wait_ms']:.3f} ms"
          + (f"  (skew {skew:.2f}x)" if skew is not None else ""),
          file=out)
    if panel["suspect"] is not None:
        print(f"  slowest rank: {panel['suspect']} (lowest wait share "
              f"— the rank the others wait for)", file=out)
    else:
        print("  no suspect (single rank or no wait-time data)",
              file=out)


def report_live(uri: str) -> int:
    with urllib.request.urlopen(uri.rstrip("/") + "/status",
                                timeout=10) as resp:
        doc = json.loads(resp.read().decode())
    found = 0
    for job in doc.get("jobs", []):
        panel = job.get("straggler")
        if panel:
            _print_panel(job["jobid"], panel)
            found += 1
    if not found:
        print("no straggler panels yet (no rank has pushed latency "
              "histograms — is the metrics uplink armed?)")
    return 0 if found else 1


def _sums_from_hists(hists: dict) -> tuple[float, float, float]:
    """(arena-wait sum, publish sum, coll-dispatch sum) in ns from one
    rank's dumped series map (label variants folded per base)."""
    wait = pub = busy = 0.0
    for key, vec in hists.items():
        base = key.split("{", 1)[0]
        if not vec:
            continue
        if base == "coll_arena_wait_ns":
            wait += vec[-1]
        elif base == "coll_ppublish_ns":
            pub += vec[-1]
        elif base == "coll_dispatch_ns":
            busy += vec[-1]
    return wait, pub, busy


def report_offline(trace_dir: str) -> int:
    by_job: dict[int, dict[int, tuple[float, float, float]]] = {}
    for path in sorted(glob.glob(
            os.path.join(trace_dir, "ompi_tpu_trace_*_rank*.json"))):
        m = _DUMP_RE.search(path)
        if not m:
            continue
        jobid, rank = int(m.group(1)), int(m.group(2))
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            hists = doc.get("otherData", {}).get("hists", {})
        except (OSError, ValueError):
            continue
        by_job.setdefault(jobid, {})[rank] = _sums_from_hists(hists)
    if not by_job:
        print(f"no per-rank dumps with histogram data under "
              f"{trace_dir!r}")
        return 1
    for jobid in sorted(by_job):
        ranks = by_job[jobid]
        waits = {r: w for r, (w, _p, _b) in ranks.items()}
        signal = "arena_wait"
        if not any(waits.values()):
            waits = {r: b for r, (_w, _p, b) in ranks.items()}
            signal = "coll_dispatch"
        pubs = {r: p for r, (_w, p, _b) in ranks.items()}
        panel = straggler_panel(waits, pubs, signal, window_s=0.0)
        if panel is None:
            continue
        _print_panel(jobid, panel)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(
        description="per-rank collective wait/publish breakdown with a "
                    "named straggler suspect")
    ap.add_argument("--uri", default=None,
                    help="DVM metrics endpoint (http://host:port); "
                    "default: read <dvm-uri>.metrics")
    ap.add_argument("--dir", default=None,
                    help="offline mode: directory of per-rank "
                    "ompi_tpu_trace_*_rank*.json dumps")
    args = ap.parse_args()
    if args.dir:
        return report_offline(args.dir)
    try:
        uri = args.uri or _default_uri()
    except OSError:
        print("no DVM metrics endpoint found (start one with: tpurun "
              "--dvm-start --metrics-port 0), or use --dir for offline "
              "dump analysis", file=sys.stderr)
        return 2
    return report_live(uri)


if __name__ == "__main__":
    sys.exit(main())
