#!/usr/bin/env python
"""hang_doctor — "my job is stuck: which rank, in which collective,
waiting on whom?" answered live or postmortem.

Live (a standing DVM with ``--metrics-port``): triggers an on-demand
cross-rank capture through the DVM's ``/doctor`` endpoint — every rank's
collective-recorder tail, pending p2p, arena counters and thread stacks,
folded into a verdict (mismatch / deadlock / straggler) by the HNP
analyzer:

    python tools/hang_doctor.py --uri $TMPDIR/ompi_tpu-dvm-<uid>.uri
    python tools/hang_doctor.py --uri http://127.0.0.1:9090

Offline (the job already died / was killed): reads the per-rank crash
trace dumps (``ompi_tpu_trace_<jobid>_rank<r>.json`` — their
``otherData.collrec`` recorder tails) and runs the SAME analyzer, so the
postmortem works from artifacts alone:

    python tools/hang_doctor.py --dir $TMPDIR --jobid 7

``--expect kind[:rank]`` turns the run into an assertion (CI / chaos
drivers): exit 0 only when the verdict matches.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import trace_export  # noqa: E402 — owns the dump filename pattern

from ompi_tpu.runtime import doctor  # noqa: E402

_RANK_RE = trace_export._RANK_RE


# ---------------------------------------------------------------------------
# live mode
# ---------------------------------------------------------------------------

def _metrics_base(uri: str) -> str:
    """Resolve --uri into the metrics http base: an http URL passes
    through; a DVM uri file (or its directory default) reads the
    recorded ``<uri>.metrics`` sidecar."""
    if uri.startswith("http://") or uri.startswith("https://"):
        return uri.rstrip("/")
    path = uri if uri.endswith(".metrics") else uri + ".metrics"
    if not os.path.exists(path):
        raise SystemExit(f"hang_doctor: no metrics endpoint recorded at "
                         f"{path} (DVM started with --metrics-port?)")
    with open(path, encoding="utf-8") as f:
        return f.read().strip().rstrip("/")


def live_doc(uri: str, timeout: float = 30.0) -> dict:
    base = _metrics_base(uri)
    with urllib.request.urlopen(f"{base}/doctor", timeout=timeout) as r:
        return json.load(r)


# ---------------------------------------------------------------------------
# offline mode (crash trace dumps)
# ---------------------------------------------------------------------------

def _cur_from_tail(rank: int, tail: list) -> dict | None:
    """Reconstruct the recorder head from a dump's record tail: the
    newest post and whether its (cid, seq) ever completed."""
    posts: list[tuple[int, int, str]] = []
    done_keys = set()
    err_keys = set()
    for rec in tail:
        try:
            _ts, r, cid, seq, kind, phase = rec[:6]
        except (TypeError, ValueError):
            continue
        if int(r) != rank:
            continue
        if phase == "post":
            posts.append((int(cid), int(seq), str(kind)))
        elif phase == "done":
            done_keys.add((int(cid), int(seq)))
        elif phase == "err":
            # an err-closed op (coll_shm_timeout, revoke) is a FAILED
            # wait, not a completion — its wait-for evidence stands
            err_keys.add((int(cid), int(seq)))
    if not posts:
        return None
    # the wedged op is the newest UNCLOSED post — NOT simply the newest
    # post: a composed outer collective's nested sub-dispatch may have
    # completed after it (the live path resolves this via the recorder
    # stack; offline must re-derive it).  Failing that, the newest
    # err-closed post (a failed wait still carries its edge), else the
    # newest post outright.
    closed = done_keys | err_keys
    pick = next((p for p in reversed(posts)
                 if (p[0], p[1]) not in closed), None)
    if pick is None:
        pick = next((p for p in reversed(posts)
                     if (p[0], p[1]) in err_keys), posts[-1])
    cid, seq, kind = pick
    cur = {"cid": cid, "seq": seq, "kind": kind,
           "done": (cid, seq) in done_keys}
    if (cid, seq) in err_keys:
        cur["err"] = True
    return cur


def offline_captures(paths: list[str]) -> list[dict]:
    captures = []
    for path in paths:
        try:
            with open(path, encoding="utf-8") as f:
                dump = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"hang_doctor: skipping {path}: {e}", file=sys.stderr)
            continue
        other = (dump.get("otherData") or {}) if isinstance(dump, dict) \
            else {}
        rank = other.get("rank")
        if rank is None:
            m = _RANK_RE.search(os.path.basename(path))
            rank = int(m.group(2)) if m else -1
        tail = other.get("collrec") or []
        cap = {"rank": int(rank), "collrec": tail,
               "stuck": (other.get("counters") or {})
               .get("coll_stuck_events_total", 0)}
        cur = _cur_from_tail(int(rank), tail)
        if cur is not None:
            cap["cur"] = cur
        captures.append(cap)
    return captures


def offline_doc(dump_dir: str, jobid: int | None) -> dict:
    pat = trace_export.dump_glob(jobid)
    paths = sorted(glob.glob(os.path.join(dump_dir, pat)))
    if not paths:
        raise SystemExit(f"hang_doctor: no trace dumps matching {pat} "
                         f"under {dump_dir}")
    jobids = {m.group(1) for p in paths
              for m in (_RANK_RE.search(os.path.basename(p)),) if m}
    if jobid is None and len(jobids) > 1:
        print(f"hang_doctor: WARNING: dumps from several jobs "
              f"{sorted(jobids)} — pass --jobid", file=sys.stderr)
    doc = doctor.analyze(offline_captures(paths))
    doc["trigger"] = "offline"
    doc["dumps"] = [os.path.basename(p) for p in paths]
    return doc


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render(doc: dict) -> str:
    v = doc.get("verdict") or {}
    kind = v.get("kind", "?")
    lines = [f"hang doctor verdict: {kind.upper()}"
             + (f" — rank {v['rank']}" if "rank" in v else "")]
    if v.get("detail"):
        lines.append(f"  {v['detail']}")
    if "op_seq" in v or "in" in v:
        lines.append(f"  in: {v.get('in', v.get('kinds'))}"
                     f"#{v.get('op_seq')} (cid {v.get('cid')})")
    if v.get("kinds"):
        lines.append("  kinds by rank: " + ", ".join(
            f"{r}={k}" for r, k in sorted(v["kinds"].items())))
    if v.get("cycle"):
        lines.append("  cycle: " + " -> ".join(map(str, v["cycle"])))
    if v.get("waiters"):
        lines.append("  waiters: " + ", ".join(
            f"{r}->{t}" for r, t in sorted(v["waiters"].items()) if t))
    if v.get("proc"):
        lines.append(f"  /proc evidence: {v['proc']}")
    stack = v.get("stack")
    if stack:
        lines.append("  stack of the named rank:")
        lines += ["    " + ln for ln in stack.strip().splitlines()[-14:]]
    no_resp = doc.get("no_response")
    if no_resp:
        lines.append(f"  no response from ranks {no_resp} "
                     f"(frozen pids cannot answer)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--uri", default=None,
                   help="live mode: DVM uri file, <uri>.metrics file, "
                        "or the metrics http base URL")
    p.add_argument("--dir", default=None,
                   help="offline mode: directory holding per-rank "
                        "ompi_tpu_trace_*_rank*.json crash dumps")
    p.add_argument("--jobid", type=int, default=None,
                   help="with --dir: only this job's dumps")
    p.add_argument("--json", action="store_true",
                   help="print the raw verdict document")
    p.add_argument("--expect", default=None, metavar="KIND[:RANK]",
                   help="assert the verdict (e.g. straggler:2 or "
                        "mismatch); nonzero exit on a miss")
    args = p.parse_args(argv)

    if bool(args.uri) == bool(args.dir):
        p.error("exactly one of --uri (live) or --dir (offline)")
    doc = live_doc(args.uri) if args.uri else offline_doc(args.dir,
                                                          args.jobid)
    print(json.dumps(doc, indent=1) if args.json else render(doc))
    if args.expect:
        want_kind, _, want_rank = args.expect.partition(":")
        v = doc.get("verdict") or {}
        if v.get("kind") != want_kind:
            print(f"hang_doctor: EXPECT FAILED: verdict "
                  f"{v.get('kind')!r} != {want_kind!r}", file=sys.stderr)
            return 1
        if want_rank and int(v.get("rank", -1)) != int(want_rank):
            print(f"hang_doctor: EXPECT FAILED: rank "
                  f"{v.get('rank')} != {want_rank}", file=sys.stderr)
            return 1
        print(f"hang_doctor: expectation {args.expect!r} met")
    return 0


if __name__ == "__main__":
    sys.exit(main())
