"""killorphans — find (and optionally reap) orphaned ompi_tpu processes.

The bench-poisoning failure mode that bit twice (CHANGES.md, PRs 9-10):
a dead session leaves PPID-1 ranks/orteds/chaos children spinning —
dozens of them eating most of the box — and every later benchmark or
tier-1 run silently measures scheduler contention instead of the code.
Both incidents were diagnosed by hand with ``ps -eo pid,ppid,etime``;
this tool makes the check mechanical:

- ``python tools/killorphans.py``            list suspects (exit 1 if any)
- ``python tools/killorphans.py --kill``     SIGKILL suspects
- ``python tools/killorphans.py --min-age 600``  age floor in seconds

A *suspect* is a process that (a) has been re-parented to init
(PPID 1 — its launching session is gone), (b) has an ompi_tpu-shaped
command line (the patterns below), (c) is older than ``--min-age``
(default 1 h: a legitimately daemonized standing DVM is excluded by
pattern, but the age floor keeps a just-started run safe regardless),
and (d) is not this process or an ancestor of it.

``preflight()`` is the library form: tools that measure (coll_bench,
chaos_soak) call it under ``--guard`` to refuse to bench a poisoned
box — orphans ADD latency noise, so a guard failure means the numbers
would have been garbage, not merely slow.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Optional

#: command-line fragments that mark a process as OURS — anchored to
#: this repo's actual entry points ("ompi_tpu" rides the module path
#: of every rank/orted we spawn; the tool scripts match by file name),
#: NOT loose tokens: a bare "orted" would reap a genuine Open MPI
#: daemon, and a bare "coll_bench" would match `tail -f
#: coll_bench.log`.  The standing DVM (tpurun --dvm-start) is
#: deliberately daemonized and EXCLUDED — killing a live pool because
#: its launcher exited would be a bug.
PATTERNS = ("ompi_tpu", "tpurun", "chaos_soak.py", "coll_bench.py")
EXCLUDE = ("--dvm-start", "killorphans")

#: default age floor: an hours-old PPID-1 rank is debris, a
#: seconds-old one may be a worker mid-handoff
DEFAULT_MIN_AGE_S = 3600.0


def _my_ancestry() -> set:
    """This process and its ancestors — never suspects (the guard may
    itself run under a tool whose name matches the patterns)."""
    pids = set()
    pid = os.getpid()
    for _ in range(32):
        pids.add(pid)
        try:
            with open(f"/proc/{pid}/stat", encoding="utf-8",
                      errors="replace") as f:
                # field 4 (after the parenthesized comm, which may
                # contain spaces) is ppid
                stat = f.read()
            pid = int(stat.rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            break
        if pid <= 1:
            break
    return pids


def find_orphans(min_age_s: float = DEFAULT_MIN_AGE_S) -> list[dict]:
    """PPID-1 ompi_tpu-shaped processes older than ``min_age_s``:
    ``[{pid, age_s, args}, ...]``, oldest first."""
    try:
        out = subprocess.run(
            ["ps", "-eo", "pid=,ppid=,etimes=,args="],
            capture_output=True, text=True, timeout=10).stdout
    except (OSError, subprocess.SubprocessError):
        return []
    mine = _my_ancestry()
    orphans = []
    for line in out.splitlines():
        fields = line.split(None, 3)
        if len(fields) < 4:
            continue
        try:
            pid, ppid, age = int(fields[0]), int(fields[1]), int(fields[2])
        except ValueError:
            continue
        args = fields[3]
        if (ppid != 1 or pid in mine or age < min_age_s
                or not any(p in args for p in PATTERNS)
                or any(e in args for e in EXCLUDE)):
            continue
        orphans.append({"pid": pid, "age_s": age, "args": args[:160]})
    orphans.sort(key=lambda o: -o["age_s"])
    return orphans


def kill_orphans(orphans: list[dict]) -> int:
    """SIGKILL every suspect; returns how many signals landed."""
    n = 0
    for o in orphans:
        try:
            os.kill(o["pid"], signal.SIGKILL)
            n += 1
        except (ProcessLookupError, PermissionError):
            pass
    return n


def preflight(tool: str, kill: bool = False,
              min_age_s: float = DEFAULT_MIN_AGE_S,
              out=sys.stderr) -> bool:
    """Bench-guard: True ⇒ the box is clean (or was just cleaned).
    False ⇒ orphans are present and were NOT killed — the caller
    should refuse to produce numbers (they would measure the orphans'
    scheduler noise, not the code)."""
    orphans = find_orphans(min_age_s)
    if not orphans:
        return True
    print(f"{tool}: {len(orphans)} orphaned ompi_tpu process(es) "
          f"(PPID 1, >{min_age_s / 3600:.1f}h old) are eating this box:",
          file=out)
    for o in orphans:
        print(f"  pid {o['pid']:>7}  age {o['age_s'] / 3600:6.1f}h  "
              f"{o['args']}", file=out)
    if kill:
        n = kill_orphans(orphans)
        print(f"{tool}: killed {n}/{len(orphans)} "
              f"(guard --kill)", file=out)
        time.sleep(0.2)   # give the scheduler a beat to reap
        return True
    print(f"{tool}: refusing to bench a poisoned box — run "
          f"`python tools/killorphans.py --kill` (or pass the tool's "
          f"--guard-kill) first", file=out)
    return False


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="find/kill hours-old PPID-1 orphaned ompi_tpu "
        "ranks and orteds (the bench-poisoning debris dead sessions "
        "leave behind)")
    ap.add_argument("--kill", action="store_true",
                    help="SIGKILL the suspects instead of only listing")
    ap.add_argument("--min-age", type=float, default=DEFAULT_MIN_AGE_S,
                    help="age floor in seconds (default 3600)")
    args = ap.parse_args(argv)

    orphans = find_orphans(args.min_age)
    if not orphans:
        print("no orphaned ompi_tpu processes")
        return 0
    for o in orphans:
        print(f"pid {o['pid']:>7}  age {o['age_s'] / 3600:6.1f}h  "
              f"{o['args']}")
    if args.kill:
        n = kill_orphans(orphans)
        print(f"killed {n}/{len(orphans)}")
        return 0
    print(f"{len(orphans)} suspect(s); re-run with --kill to reap")
    return 1


if __name__ == "__main__":
    sys.exit(main())
