"""chaos_soak — seeded fault plans vs. every recovery policy we ship.

Generates K fault plans from one seed, runs each against the policy it
targets, and asserts the job lands in that policy's *defined* state:

- ``respawn``       — a rank is killed mid-ring; errmgr revives it, it
  restores from its ckpt snapshot, the job exits 0 with the exact accs.
- ``notify-shrink`` — a rank is killed mid-allreduce under ``--mca
  errmgr notify`` (optionally with seeded FT-frame drops); survivors
  revoke + agree + shrink + resume, every survivor prints the SAME,
  recomputable final acc, exit 0.
- ``continue``      — a rank is killed under ``--mca errmgr continue``;
  survivors (whose work never depended on it) finish, exit 0.
- ``abort``         — the default policy: the kill tears the whole job
  down; exit is nonzero and the abort help text names the dead rank.
- ``midtree-kill``  — a NON-LEAF orted is SIGKILLed on the sim daemon
  tree under ``notify``: its orphaned child daemons re-parent to the
  grandparent (TAG_REPARENT handshake, HNP arbitrating) instead of
  applying the lifeline teardown, so every other host's ranks finish
  and the job exits 0 — loss confined to the dead host.
- ``rank-hang``     — a rank SIGSTOPs mid-run (alive pid, silent rank:
  invisible to the daemon heartbeat layer); rank-plane gossip
  heartbeats declare it suspect, survivors shrink and finish with the
  same recomputed acc a kill would give, and the reported pid is
  reaped so the job exits 0.
- ``writer-death``  — a rank dies mid-collective inside the coll/shm
  arena with runtime dead-set polling crippled: the arena wait's btl
  pid probe surfaces MPI_ERR_PROC_FAILED in ~the probe grace (the
  driver asserts the printed time-to-error stays far below the 60 s
  ``coll_shm_timeout``), then the normal shrink recipe finishes.
- ``selfheal-hang`` — a rank SIGSTOPs mid-ring under ``--mca errmgr
  selfheal``: gossip declares it, the control plane reaps the hung pid,
  the errmgr revives it in place, it restores from its last snapshot
  (``snapc.auto_restore``) and the msglog replays the in-flight gap —
  every rank (victim included) finishes with the full-ring acc, and the
  survivors' printed failure→success gap (``heal_dt``) bounds the
  detect→rejoin cycle under 15 s.
- ``coll-hang``     — a rank stalls INSIDE its Kth collective
  (``stall@coll=K``, spin mode so its crash dump still flushes); the
  survivors wedge in the arena until ``coll_shm_timeout`` aborts the
  job, and the OFFLINE hang doctor (``tools/hang_doctor.py --dir``)
  must name the stalled rank as the straggler from the per-rank crash
  dumps alone — the postmortem-doctor acceptance class.
- ``selfheal-coll``  — the collective-capable rejoin prover: a 4-rank
  allreduce loop under ``--mca errmgr selfheal`` whose victim dies at
  its Nth top-level collective dispatch (``kill@coll=N`` — inside the
  dispatch, before publishing).  Survivors' allreduces fail fast, the
  errmgr revives the victim, it restores from its snapshot, and the
  survivors' epoch-fenced rebuild re-runs the node split + arena
  bootstrap with the revived rank included — every rank (victim too)
  converges to FULL-WORLD answers on the shm arena (``fallback=0``,
  mode ``arena``) with exactly one rejoin per survivor.
- ``selfheal-crashloop`` — a rank dies at the same step in EVERY life
  (the ``crash`` fault kind): the revive budget burns with backoff
  (min-uptime gating forced on via ``errmgr_min_uptime_s``), the policy
  escalates revive → shrink, survivors finish and the job exits 0
  smaller — with exactly ``errmgr_max_restarts`` revive events and one
  escalation event in the notifier stream.

No run may hang (every subprocess has a hard timeout — a timeout is a
soak failure), and no run may print a wrong answer (expected values are
recomputed by this driver from the plan, never trusted from the app).

``--canary`` flips the harness from single-shot launches to a standing
multi-tenant pool: one DVM (``--mca errmgr selfheal``) serves every
cycle, and each cycle submits TWO concurrent tenants — a chaos victim
running a seeded selfheal-class fault (kill@step / hang@step /
kill@coll, rotating) and a fault-free canary ring.  Both must exit 0
with their exact recomputed accs: the victim proves in-place recovery
works through the shared daemon tree, the canary proves ZERO
interference (its answers never wobble while its co-tenant is being
healed next door).  errmgr is a VM-level selection on a standing DVM,
so only selfheal-compatible classes rotate here; the doctor-driven
remediation ladder (SIGCONT probe / requeue / reject) is exercised by
the pool-smoke CI job and tests/runtime/test_dvm_sched.py, not this
mode — the canary pins ``dvm_remediate 0`` so the two recovery layers
are proven separately, not racing each other.

Replay determinism: each plan's first run is replayed with the same seed
and the fault logs are compared — injected kills must reproduce exactly
(same rank, same trigger step), and every frame verdict in both logs
must recompute to the same decision through the injector's pure hash
(``faultinject._u01``), which is the property that makes a plan a
*schedule* rather than a dice roll.

    python tools/chaos_soak.py --plans 20 --seed 0
"""

from __future__ import annotations

import argparse
import json
import os
import random
import re
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from ompi_tpu.testing import faultinject  # noqa: E402

POLICIES = ("respawn", "notify-shrink", "continue", "abort",
            "midtree-kill", "rank-hang", "writer-death",
            "selfheal-hang", "selfheal-crashloop", "coll-hang",
            "selfheal-coll")

RING_APP = r"""
import os
import numpy as np
import ompi_tpu
from ompi_tpu.ckpt.msglog import MessageLog
from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.testing import faultinject

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
store = SnapshotStore(os.environ["CKPT_DIR"], job=f"rank{rank}")
restarted = int(os.environ.get("OMPI_TPU_RESTART", "0"))
log = MessageLog(comm).attach(auto_replay=True)

start, acc = 0, 0.0
if restarted:
    seq = store.latest()
    if seq is not None:
        state = store.load_rank(seq, 0)
        start, acc = int(state["step"]) + 1, float(state["acc"])
    print(f"rank {rank} resumed at step {start}", flush=True)

right, left = (rank + 1) % size, (rank - 1) % size
steps = int(os.environ["SOAK_STEPS"])
for step in range(start, steps):
    faultinject.step()
    out = np.array([float(rank * 100 + step)])
    sreq = comm.isend(out, dest=right, tag=step)
    got = comm.recv(source=left, tag=step)
    sreq.wait()
    assert float(got[0]) == left * 100 + step, (step, got)
    acc += float(got[0])
    store.write_rank(step, 0, {"step": np.int64(step),
                               "acc": np.float64(acc)})
    store.commit(step, 1)

print(f"rank {rank} ring done acc={acc:.0f}", flush=True)
ompi_tpu.finalize()
"""

LOCAL_APP = r"""
import numpy as np
import ompi_tpu
from ompi_tpu.testing import faultinject

comm = ompi_tpu.init()
rank = comm.rank
import os
steps = int(os.environ["SOAK_STEPS"])
acc = 0.0
for step in range(steps):
    faultinject.step()
    acc += float(rank * 10 + step)
print(f"rank {rank} local done acc={acc:.0f}", flush=True)
ompi_tpu.finalize()
"""

# the mid-tree plan: one rank per sim host, long enough past init that
# the injected daemon SIGKILL lands while ranks are quietly working —
# the orphaned daemons' ranks must keep running through the re-parenting
MIDTREE_APP = r"""
import time
import ompi_tpu

comm = ompi_tpu.init()
time.sleep(14.0)
print(f"rank {comm.rank} survived", flush=True)
ompi_tpu.finalize()
"""

# the selfheal ring: same traffic as RING_APP, but under errmgr selfheal
# a peer's death is TRANSIENT (the errmgr is already reviving it) — ops
# that fail with PROC_FAILED retry until the revive lands, and the first
# failure→success gap per rank is printed so the driver can bound the
# whole detect→reap→revive→rejoin cycle
SELFHEAL_APP = r"""
import os, time
import numpy as np
import ompi_tpu
from ompi_tpu.ckpt import snapc
from ompi_tpu.ckpt.msglog import MessageLog
from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.mpi.constants import ERR_PROC_FAILED, MPIException
from ompi_tpu.testing import faultinject

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
store = SnapshotStore(os.environ["CKPT_DIR"], job=f"rank{rank}")
log = MessageLog(comm).attach(auto_replay=True)

start, acc = 0, 0.0
restored = snapc.auto_restore(comm, store, rank=0)
if restored is not None:
    seq, state = restored
    start, acc = int(state["step"]) + 1, float(state["acc"])
    print(f"rank {rank} resumed at step {start} from snapshot {seq}",
          flush=True)

_t0 = [None]
def heal_retry(fn):
    # retry granularity is ONE operation: a send that died with the old
    # incarnation is re-sent (a duplicate of a delivered one parks
    # harmlessly — per-step tags never re-match), while a recv retries
    # only if it never returned — wrapping a whole send+recv step would
    # re-post a recv whose message the first attempt already consumed
    while True:
        try:
            out = fn()
            if _t0[0] is not None:
                print(f"rank {rank} heal_dt={time.monotonic() - _t0[0]:.2f}",
                      flush=True)
                _t0[0] = None
            return out
        except MPIException as e:
            if e.error_class != ERR_PROC_FAILED:
                raise
            if _t0[0] is None:
                _t0[0] = time.monotonic()
            time.sleep(0.1)

steps = int(os.environ["SOAK_STEPS"])
right, left = (rank + 1) % size, (rank - 1) % size
for step in range(start, steps):
    faultinject.step()
    out = np.array([float(rank * 100 + step)])
    heal_retry(lambda: comm.isend(out, dest=right, tag=step).wait())
    got = heal_retry(lambda: comm.recv(source=left, tag=step))
    assert float(got[0]) == left * 100 + step, (step, got)
    acc += float(got[0])
    store.write_rank(step, 0, {"step": np.int64(step),
                               "acc": np.float64(acc)})
    store.commit(step, 1)

print(f"rank {rank} selfheal done acc={acc:.0f}", flush=True)
ompi_tpu.finalize()
"""

# the coll-hang app: one small allreduce per step — the victim's
# stall@coll freezes it mid-dispatch, everyone else wedges in the arena
COLLHANG_APP = r"""
import os
import numpy as np
import ompi_tpu
from ompi_tpu.testing import faultinject

comm = ompi_tpu.init()
steps = int(os.environ["SOAK_STEPS"])
acc = 0.0
for step in range(steps):
    faultinject.step()
    acc += float(comm.allreduce(np.full(32, float(comm.rank + step)))[0])
print(f"rank {comm.rank} collhang done acc={acc:.0f}", flush=True)
ompi_tpu.finalize()
"""

# the collective-capable rejoin prover: an allreduce loop under errmgr
# selfheal.  The victim dies INSIDE its Nth top-level collective
# dispatch (kill@coll — after the recorder post, before publishing into
# the arena), survivors' in-flight allreduces fail fast, and once the
# revive is adopted the epoch-fenced rebuild re-runs the node split +
# arena bootstrap with the revived rank included: every step's answer
# is FULL-world, the provider stays the shm arena (no host fallback),
# and each survivor records exactly one coll_rejoin
SELFHEAL_COLL_APP = r"""
import os, time
import numpy as np
import ompi_tpu
from ompi_tpu.ckpt import snapc
from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.mpi import trace
from ompi_tpu.mpi.constants import ERR_PROC_FAILED, MPIException
from ompi_tpu.testing import faultinject

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
store = SnapshotStore(os.environ["CKPT_DIR"], job=f"rank{rank}")

start, acc = 0, 0.0
restored = snapc.auto_restore(comm, store, rank=0)
if restored is not None:
    seq, state = restored
    start, acc = int(state["step"]) + 1, float(state["acc"])
    print(f"rank {rank} resumed at step {start}", flush=True)

def heal_retry(fn):
    # a collective is atomic at the app level: a failed attempt (peer
    # died / rejoin fence) completed on NO rank, so re-running the
    # whole op is the retry unit — the epoch-fenced rebuild underneath
    # guarantees the retried op runs on fresh arena counters
    while True:
        try:
            return fn()
        except MPIException as e:
            if e.error_class != ERR_PROC_FAILED:
                raise
            time.sleep(0.1)

steps = int(os.environ["SOAK_STEPS"])
for step in range(start, steps):
    faultinject.step()
    out = heal_retry(
        lambda: comm.allreduce(np.full(8, float(rank * 100 + step))))
    acc += float(out[0])
    store.write_rank(step, 0, {"step": np.int64(step),
                               "acc": np.float64(acc)})
    store.commit(step, 1)

st = comm._coll_shm_state
print(f"rank {rank} collrejoin done acc={acc:.0f} "
      f"mode={getattr(st, 'mode', '?')} "
      f"fallback={trace.counters['coll_shm_fallback_total']} "
      f"rejoins={trace.counters['coll_rejoin_total']}", flush=True)
ompi_tpu.finalize()
"""

# the crash-loop prover: the victim dies at the SAME step in every life
# (fault kind ``crash``), survivors do independent local work — the
# job's fate rides entirely on the selfheal ladder escalating
# revive → shrink instead of aborting or reviving forever
CRASHLOOP_APP = r"""
import os, time
import ompi_tpu
from ompi_tpu.testing import faultinject

comm = ompi_tpu.init()
rank = comm.rank
steps = int(os.environ["SOAK_STEPS"])
acc = 0.0
for step in range(steps):
    faultinject.step()
    acc += float(rank * 10 + step)
    time.sleep(0.2)
print(f"rank {rank} crashloop done acc={acc:.0f}", flush=True)
ompi_tpu.finalize()
"""


def tpurun(args, env_extra=None, timeout=150):
    env = dict(os.environ)
    env.pop("OMPI_TPU_RANK", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def gen_plan(seed: int, idx: int, np_: int, steps: int) -> dict:
    """Plan idx of the soak: policy + victim + kill step + drop rate,
    all drawn from the seeded stream."""
    rng = random.Random(f"{seed}:{idx}")  # str seed: tuples raise on 3.11+
    policy = POLICIES[idx % len(POLICIES)]
    victim = rng.randrange(0, np_) \
        if policy in ("notify-shrink", "rank-hang", "writer-death",
                      "selfheal-hang", "selfheal-crashloop") \
        else rng.randrange(1, np_)
    kill_step = rng.randrange(1, steps - 1)
    drop = rng.choice((0.0, 0.05, 0.15)) if policy == "notify-shrink" \
        else 0.0
    if policy == "midtree-kill":
        # daemon 1 is the canonical mid-tree node of the 4-host binary
        # routing tree (children 3 and 4).  The kill is keyed on the
        # ranks-registered barrier (PMIx reg count), not wall-clock: on
        # a slow box a fixed t=6–8 s could land while 4 jax ranks were
        # still importing, turning a containment test into an init
        # abort — @reg=4 cannot fire before every rank finished booting
        kill_after = round(rng.uniform(1.0, 2.0), 1)
        return {"idx": idx, "policy": policy, "victim": 1,
                "kill_step": None, "kill_after": kill_after, "drop": 0.0,
                "plan": f"daemon=1:kill@reg=4:after={kill_after}",
                "seed": seed}
    if policy == "coll-hang":
        # the stall ordinal counts RECORDED dispatches (init barrier is
        # ordinal 0, every app step issues >= 1), so any K in [1, steps]
        # lands mid-run on every box
        victim = rng.randrange(0, np_)
        coll_n = rng.randrange(1, steps)
        return {"idx": idx, "policy": policy, "victim": victim,
                "kill_step": coll_n, "drop": 0.0,
                "plan": f"rank={victim}:stall@coll={coll_n}",
                "seed": seed}
    if policy == "selfheal-coll":
        # the victim's top-level dispatch ordinals: init barrier = 0,
        # step s allreduce = s + 1 — any N in [2, steps-1] dies at app
        # step N-1 with at least one committed snapshot behind it and
        # at least one full-world step after the rejoin
        victim = rng.randrange(0, np_)
        coll_n = rng.randrange(2, steps)
        return {"idx": idx, "policy": policy, "victim": victim,
                "kill_step": coll_n - 1, "drop": 0.0,
                "plan": f"rank={victim}:kill@coll={coll_n}",
                "seed": seed}
    if policy in ("rank-hang", "selfheal-hang"):
        plan = f"rank={victim}:hang@step={kill_step}"
    elif policy == "selfheal-crashloop":
        plan = f"rank={victim}:crash@step={kill_step}"
    else:
        plan = f"rank={victim}:kill@step={kill_step}"
    if drop:
        plan += f";drop={drop}"
    return {"idx": idx, "policy": policy, "victim": victim,
            "kill_step": kill_step, "drop": drop, "plan": plan,
            "seed": seed}


def expected_shrink_acc(np_: int, steps: int, victim: int,
                        kill_step: int) -> float:
    """The acc every shrink_allreduce survivor must print: full-world
    sums for agreed steps before the kill, survivor sums from it on."""
    acc = 0.0
    for s in range(steps):
        ids = range(np_) if s < kill_step else \
            [i for i in range(np_) if i != victim]
        acc += sum(i * 10 + s for i in ids)
    return acc


def _assert_shrink_out(r, plan: dict, np_: int, steps: int) -> str:
    """Shared shrink-and-continue postcondition: exit 0 and every
    survivor prints the recomputed acc (a hang at step K and a kill at
    step K account identically — the victim froze/died BEFORE
    contributing step K, so agreed steps < K are full-world)."""
    out = r.stdout + r.stderr
    assert r.returncode == 0, \
        f"{plan['policy']} rc={r.returncode}: {out[-2000:]}"
    want = expected_shrink_acc(np_, steps, plan["victim"],
                               plan["kill_step"])
    survivors = [i for i in range(np_) if i != plan["victim"]]
    for rank in survivors:
        line = (f"id {rank} final acc={want:.0f} "
                f"size={len(survivors)} shrinks=1")
        assert line in out, (line, out[-2000:])
    return out


def run_plan(plan: dict, np_: int, steps: int, log_dir: str,
             verbose: bool) -> None:
    policy = plan["policy"]
    ck = tempfile.mkdtemp(prefix=f"chaos_ck_{plan['idx']}_")
    env = {"CKPT_DIR": ck, "SOAK_STEPS": str(steps),
           "SHRINK_DEMO_STEPS": str(steps),
           "OMPI_TPU_FAULT_LOG_DIR": log_dir}
    mca = ["--mca", "faultinject_plan", plan["plan"],
           "--mca", "faultinject_seed", str(plan["seed"])]
    if policy == "respawn":
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "respawn", *mca,
                    "--", sys.executable, "-c", RING_APP], env)
        out = r.stdout + r.stderr
        assert r.returncode == 0, f"respawn rc={r.returncode}: {out[-2000:]}"
        assert f"rank {plan['victim']} resumed at step" in out, out[-2000:]
        for rank in range(np_):
            acc = sum(((rank - 1) % np_) * 100 + s for s in range(steps))
            assert f"rank {rank} ring done acc={acc:.0f}" in out, \
                (rank, acc, out[-2000:])
    elif policy == "notify-shrink":
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "notify", *mca,
                    "--", sys.executable,
                    os.path.join(REPO, "examples", "shrink_allreduce.py")],
                   env)
        _assert_shrink_out(r, plan, np_, steps)
    elif policy == "rank-hang":
        # SIGSTOP'd rank: alive pid, silent peer — only the rank-plane
        # gossip heartbeats can see it.  Survivors shrink and finish with
        # the SAME acc a kill at that step gives; the reported pid is
        # reaped via the control plane so the job still exits 0.
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "notify",
                    "--mca", "ft_gossip_period", "0.3",
                    "--mca", "ft_gossip_timeout", "2.0", *mca,
                    "--", sys.executable,
                    os.path.join(REPO, "examples", "shrink_allreduce.py")],
                   env, timeout=240)
        _assert_shrink_out(r, plan, np_, steps)
    elif policy == "writer-death":
        # the arena writer dies mid-collective while runtime dead-set
        # polling is crippled (ft_poll_period 30): the btl pid probe in
        # the arena wait is what must surface the failure — the printed
        # time-to-error stays in the probe window, not the 60 s timeout
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "notify",
                    "--mca", "ft_poll_period", "30",
                    "--mca", "coll_shm_probe_grace", "1.0", *mca,
                    "--", sys.executable,
                    os.path.join(REPO, "examples", "shrink_allreduce.py")],
                   env, timeout=240)
        out = _assert_shrink_out(r, plan, np_, steps)
        detects = [float(m) for m in
                   re.findall(r"detect_dt=([0-9.]+)", out)]
        assert detects, f"no detect_dt lines: {out[-2000:]}"
        assert max(detects) < 15.0, \
            (f"writer death took {max(detects):.1f}s to surface — "
             f"the arena probe should beat the 60s coll_shm_timeout")
    elif policy == "midtree-kill":
        # a NON-LEAF daemon dies: without re-parenting its whole subtree
        # (daemons 3 and 4 → ranks 2 and 3) would apply the lifeline
        # teardown; with it, only the dead host's rank is lost
        r = tpurun(["-np", "4", "--plm", "sim", "--hosts", "4",
                    "--mca", "errmgr", "notify",
                    "--mca", "multihost_auto_init", "0",
                    "--mca", "rml_heartbeat_period", "0.2",
                    "--mca", "rml_heartbeat_timeout", "2.0", *mca,
                    "--", sys.executable, "-c", MIDTREE_APP],
                   env, timeout=240)
        out = r.stdout + r.stderr
        assert r.returncode == 0, \
            f"midtree rc={r.returncode}: {out[-3000:]}"
        assert "daemon-reparent" in out, \
            f"no re-parenting event: {out[-3000:]}"
        # ranks 2 and 3 live on the ORPHANED daemons — their survival is
        # the re-parenting proof (rank 0 died with daemon 1; rank 1's
        # daemon 2 was never involved)
        for rank in (1, 2, 3):
            assert f"rank {rank} survived" in out, (rank, out[-3000:])
        assert "rank 0 survived" not in out, out[-3000:]
    elif policy == "selfheal-hang":
        # the full self-healing cycle: gossip detects the SIGSTOP, the
        # control plane reaps the pid, the errmgr revives it in place,
        # it restores from its snapshot, and the ring CONVERGES to the
        # full-world answer — nobody shrinks, nobody aborts
        # window 4 s (vs rank-hang's 2 s): a revived rank's interpreter
        # start saturates a small box's cores for seconds, and a too-
        # tight window then false-declares HEALTHY ranks mid-rejoin —
        # the detect→rejoin bound below still holds with 3x margin
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "selfheal",
                    "--mca", "ft_gossip_period", "0.5",
                    "--mca", "ft_gossip_timeout", "4.0", *mca,
                    "--", sys.executable, "-c", SELFHEAL_APP],
                   env, timeout=240)
        out = r.stdout + r.stderr
        assert r.returncode == 0, \
            f"selfheal-hang rc={r.returncode}: {out[-3000:]}"
        assert f"rank {plan['victim']} resumed at step" in out, out[-3000:]
        assert "selfheal revive" in out, \
            f"no selfheal revive event: {out[-3000:]}"
        for rank in range(np_):
            acc = sum(((rank - 1) % np_) * 100 + s for s in range(steps))
            assert f"rank {rank} selfheal done acc={acc:.0f}" in out, \
                (rank, acc, out[-3000:])
        heals = [float(m) for m in re.findall(r"heal_dt=([0-9.]+)", out)]
        assert heals, f"no heal_dt lines: {out[-3000:]}"
        assert max(heals) < 15.0, \
            (f"detect→rejoin took {max(heals):.1f}s — the gossip window "
             f"+ reap + revive + restore cycle must stay under 15s")
    elif policy == "selfheal-coll":
        # the collective-capable rejoin: victim dies INSIDE a collective,
        # revives, and the epoch-fenced rebuild lets every rank finish
        # with FULL-world answers on the shm arena — transparently to
        # the allreduce loop (only the app-level PROC_FAILED retry the
        # FT contract already requires)
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "selfheal", *mca,
                    "--", sys.executable, "-c", SELFHEAL_COLL_APP],
                   env, timeout=240)
        out = r.stdout + r.stderr
        assert r.returncode == 0, \
            f"selfheal-coll rc={r.returncode}: {out[-3000:]}"
        assert f"rank {plan['victim']} resumed at step" in out, out[-3000:]
        assert "selfheal revive" in out, \
            f"no selfheal revive event: {out[-3000:]}"
        total = sum(range(np_)) * 100
        acc = sum(total + np_ * s for s in range(steps))
        for rank in range(np_):
            # full-world answers, the shm arena (not host fallback), and
            # exactly one epoch-fenced rejoin per survivor (the revived
            # life builds FRESH state — no rejoin to count)
            want = (f"rank {rank} collrejoin done acc={acc:.0f} "
                    f"mode=arena fallback=0 "
                    f"rejoins={0 if rank == plan['victim'] else 1}")
            assert want in out, (want, out[-3000:])
    elif policy == "selfheal-crashloop":
        # the escalation ladder: the victim dies at the same step every
        # life; min-uptime gating (forced high) classifies every
        # re-death as a crash loop, the budget burns with backoff, and
        # the policy degrades revive → shrink — the job survives
        # smaller, with a deterministic revive/escalation event count
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "selfheal",
                    "--mca", "errmgr_max_restarts", "2",
                    "--mca", "errmgr_min_uptime_s", "30", *mca,
                    "--", sys.executable, "-c", CRASHLOOP_APP],
                   env, timeout=240)
        out = r.stdout + r.stderr
        assert r.returncode == 0, \
            f"selfheal-crashloop rc={r.returncode}: {out[-3000:]}"
        for rank in range(np_):
            if rank == plan["victim"]:
                continue
            acc = sum(rank * 10 + s for s in range(steps))
            assert f"rank {rank} crashloop done acc={acc:.0f}" in out, \
                (rank, out[-3000:])
        assert f"rank {plan['victim']} crashloop done" not in out, \
            f"crash-looping victim claims completion: {out[-3000:]}"
        revives = out.count("selfheal revive")
        assert revives == 2, \
            (f"expected exactly 2 revives (errmgr_max_restarts) before "
             f"escalation, saw {revives}: {out[-3000:]}")
        assert "selfheal-escalate" in out and "degrading to shrink" in out, \
            f"no revive→shrink escalation event: {out[-3000:]}"
    elif policy == "coll-hang":
        # victim stalls inside collective K (spin: its dump flushes at
        # teardown); peers wedge until coll_shm_timeout aborts the job;
        # the OFFLINE doctor must then name the victim from dumps alone
        tdir = tempfile.mkdtemp(prefix=f"chaos_doctor_{plan['idx']}_")
        r = tpurun(["-np", str(np_), "--timeout", "90",
                    "--mca", "faultinject_hang_mode", "spin",
                    "--mca", "coll_shm_timeout", "10",
                    "--mca", "coll_stuck_timeout", "2", *mca,
                    "--", sys.executable, "-c", COLLHANG_APP],
                   dict(env, TMPDIR=tdir, OMPI_TPU_TRACE="1"),
                   timeout=240)
        out = r.stdout + r.stderr
        assert r.returncode != 0, \
            f"coll-hang exited 0 despite a stalled rank: {out[-2000:]}"
        assert f"rank {plan['victim']} collhang done" not in out, \
            f"stalled victim claims completion: {out[-2000:]}"
        dr = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "hang_doctor.py"),
             "--dir", tdir, "--expect", f"straggler:{plan['victim']}"],
            capture_output=True, text=True, timeout=60)
        assert dr.returncode == 0, \
            (f"offline doctor missed the stalled rank:\n"
             f"{dr.stdout}{dr.stderr}\njob tail: {out[-1500:]}")
    elif policy == "continue":
        r = tpurun(["-np", str(np_), "--mca", "errmgr", "continue", *mca,
                    "--", sys.executable, "-c", LOCAL_APP], env)
        out = r.stdout + r.stderr
        assert r.returncode == 0, f"continue rc={r.returncode}: {out[-2000:]}"
        for rank in range(np_):
            if rank == plan["victim"]:
                continue
            acc = sum(rank * 10 + s for s in range(steps))
            assert f"rank {rank} local done acc={acc:.0f}" in out, \
                (rank, out[-2000:])
    elif policy == "abort":
        r = tpurun(["-np", str(np_), *mca,
                    "--", sys.executable, "-c", LOCAL_APP], env)
        out = r.stdout + r.stderr
        assert r.returncode != 0, \
            f"abort policy exited 0 despite a kill: {out[-2000:]}"
        assert "aborted" in out.lower(), out[-2000:]
    if verbose:
        print(f"  plan {plan['idx']:>2} [{plan['policy']}] "
              f"{plan['plan']!r}: ok")


def read_fault_logs(log_dir: str) -> dict[int, dict]:
    """Per-rank fault logs, events merged across incarnations (a
    respawned rank dumps faults_rank<r>_life<n>.json per life)."""
    logs: dict[int, dict] = {}
    for name in sorted(os.listdir(log_dir)):
        if name.startswith("faults_rank") and name.endswith(".json"):
            with open(os.path.join(log_dir, name)) as fh:
                data = json.load(fh)
            prev = logs.get(data["rank"])
            if prev is None:
                logs[data["rank"]] = data
            else:
                prev["events"] = prev["events"] + data["events"]
    return logs


def check_replay(plan: dict, first: dict[int, dict],
                 second: dict[int, dict]) -> None:
    """Replay determinism, asserted on the parts a threaded run can
    guarantee:

    - the injected KILL schedule (rank, trigger, step) must reproduce
      exactly — this is the plan's event sequence;
    - frame-fault verdicts are a pure hash of (seed, rank, peer, frame
      identity), so any identity that fired in BOTH runs must have fired
      with the SAME kind (an impure/timing-dependent verdict function
      would diverge here);
    - every logged verdict must recompute through the injector's hash at
      the acting rank's stream position.

    Full set-equality of frame events is deliberately NOT asserted:
    WHICH identities get attempted depends on retransmission timing (a
    decision frame racing a resend timer), even though each identity's
    verdict does not.
    """
    kills_a = sorted((r, e["kind"], e["trigger"], e["value"])
                     for r, d in first.items() for e in d["events"]
                     if e["kind"] in ("kill", "hang", "crash",
                                      "stall", "mismatch"))
    kills_b = sorted((r, e["kind"], e["trigger"], e["value"])
                     for r, d in second.items() for e in d["events"]
                     if e["kind"] in ("kill", "hang", "crash",
                                      "stall", "mismatch"))
    assert kills_a == kills_b, \
        f"plan {plan['idx']}: kill schedule diverged: {kills_a} vs {kills_b}"

    def frame_faults(logs):
        return {(r, e["peer"], e["frame"]): e["kind"]
                for r, d in logs.items() for e in d["events"]
                if e["kind"] in ("drop", "dup", "delay")}

    fa, fb = frame_faults(first), frame_faults(second)
    for key in fa.keys() & fb.keys():
        assert fa[key] == fb[key], \
            (f"plan {plan['idx']}: frame {key} fired as {fa[key]!r} in "
             f"one run and {fb[key]!r} in the replay — verdicts are not "
             f"a pure function of the frame identity")
    for logs in (first, second):
        for r, d in logs.items():
            for e in d["events"]:
                if e["kind"] not in ("drop", "dup", "delay"):
                    continue
                u = faultinject._u01(plan["seed"], r, e["peer"],
                                     e["frame"], e["kind"])
                p = e.get("p", plan["drop"])
                assert u < p, \
                    (f"plan {plan['idx']}: logged {e['kind']} on "
                     f"{e['frame']!r} does not recompute "
                     f"(u={u:.3f} >= p={p})")


# ---------------------------------------------------------------------------
# --canary: chaos tenants vs. a fault-free co-tenant on ONE standing pool
# ---------------------------------------------------------------------------

# the selfheal-compatible rotation: every class here heals IN PLACE
# under --mca errmgr selfheal, so the job still exits 0 and the pool
# keeps serving — exactly the faults a standing multi-tenant VM must
# absorb without its other tenants noticing
CANARY_CLASSES = ("kill", "hang", "coll")


def _canary_plan(seed: int, cycle: int, np_: int, steps: int) -> dict:
    cls = CANARY_CLASSES[cycle % len(CANARY_CLASSES)]
    rng = random.Random(f"canary:{seed}:{cycle}")
    victim = rng.randrange(0, np_)
    if cls == "coll":
        # victim's dispatch ordinals: init barrier = 0, step s = s + 1;
        # N in [2, steps-1] leaves a snapshot behind and a full-world
        # step after the rejoin (same window the selfheal-coll soak uses)
        coll_n = rng.randrange(2, steps)
        plan = f"rank={victim}:kill@coll={coll_n}"
    else:
        step = rng.randrange(1, steps - 1)
        plan = f"rank={victim}:{cls}@step={step}"
    return {"cycle": cycle, "cls": cls, "victim": victim, "plan": plan}


def _dvm_submit(uri: str, np_: int, mca: list, app: str,
                env: dict, timeout: int = 240):
    return tpurun(["--dvm-submit", "--dvm-uri", uri, "-np", str(np_),
                   *mca, "--", sys.executable, "-c", app],
                  env, timeout=timeout)


def _check_canary_chaos(plan: dict, r, np_: int, steps: int) -> None:
    out = r.stdout + r.stderr
    assert r.returncode == 0, \
        (f"canary chaos [{plan['cls']}] rc={r.returncode}: {out[-3000:]}")
    v = plan["victim"]
    # the errmgr's "selfheal revive" log line lands in the DVM SERVER
    # process, not this client's IOF — the revive is asserted instead
    # on the pool's /status FT timeline after the cycles (run_canary)
    assert f"rank {v} resumed at step" in out, out[-3000:]
    if plan["cls"] == "coll":
        total = sum(range(np_)) * 100
        acc = sum(total + np_ * s for s in range(steps))
        for rank in range(np_):
            want = (f"rank {rank} collrejoin done acc={acc:.0f} "
                    f"mode=arena fallback=0 "
                    f"rejoins={0 if rank == v else 1}")
            assert want in out, (want, out[-3000:])
    else:
        for rank in range(np_):
            acc = sum(((rank - 1) % np_) * 100 + s for s in range(steps))
            assert f"rank {rank} selfheal done acc={acc:.0f}" in out, \
                (rank, acc, out[-3000:])


def _check_canary_ring(r, np_: int, steps: int) -> None:
    """The zero-interference contract: the fault-free co-tenant's accs
    are recomputed here and must match EXACTLY — a chaos tenant being
    healed on the same daemons must not perturb a single message."""
    out = r.stdout + r.stderr
    assert r.returncode == 0, \
        f"canary co-tenant rc={r.returncode}: {out[-3000:]}"
    for rank in range(np_):
        acc = sum(((rank - 1) % np_) * 100 + s for s in range(steps))
        assert f"rank {rank} ring done acc={acc:.0f}" in out, \
            (rank, acc, out[-3000:])


def run_canary(args) -> int:
    np_, steps = args.np_, args.steps
    pool_dir = tempfile.mkdtemp(prefix="chaos_canary_")
    uri = os.path.join(pool_dir, "dvm.uri")
    env = dict(os.environ)
    env.pop("OMPI_TPU_RANK", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # --slots is the TOTAL pool: 2*np over 2 hosts lets the chaos
    # tenant and the canary ring gang-place side by side
    server = subprocess.Popen(
        [sys.executable, "-m", "ompi_tpu.tools.tpurun", "--dvm-start",
         "--hosts", "2", "--slots", str(2 * np_),
         "--metrics-port", "0",
         "--mca", "errmgr", "selfheal",
         "--mca", "dvm_remediate", "0",
         "--dvm-uri", uri],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO)
    deadline = time.monotonic() + 60
    while not os.path.exists(uri):
        if server.poll() is not None:
            print(f"canary DVM died: {server.stderr.read()[-2000:]}",
                  file=sys.stderr)
            return 1
        if time.monotonic() > deadline:
            server.kill()
            print("canary DVM uri never appeared", file=sys.stderr)
            return 1
        time.sleep(0.1)

    failures = []
    try:
        for cycle in range(args.plans):
            plan = _canary_plan(args.seed, cycle, np_, steps)
            ck_chaos = tempfile.mkdtemp(prefix=f"canary_ck_{cycle}c_")
            ck_ring = tempfile.mkdtemp(prefix=f"canary_ck_{cycle}r_")
            mca = ["--mca", "faultinject_plan", plan["plan"],
                   "--mca", "faultinject_seed", str(args.seed)]
            if plan["cls"] == "hang":
                # same gossip window the selfheal-hang soak class uses
                mca += ["--mca", "ft_gossip_period", "0.5",
                        "--mca", "ft_gossip_timeout", "4.0"]
            app = SELFHEAL_COLL_APP if plan["cls"] == "coll" \
                else SELFHEAL_APP
            res = {}

            def co_tenant():
                res["ring"] = _dvm_submit(
                    uri, np_, [], RING_APP,
                    {"CKPT_DIR": ck_ring, "SOAK_STEPS": str(steps)})

            t = threading.Thread(target=co_tenant, daemon=True)
            t.start()
            try:
                chaos = _dvm_submit(
                    uri, np_, mca, app,
                    {"CKPT_DIR": ck_chaos, "SOAK_STEPS": str(steps)})
                t.join(timeout=260)
                assert not t.is_alive(), "co-tenant submission hung"
                _check_canary_chaos(plan, chaos, np_, steps)
                _check_canary_ring(res["ring"], np_, steps)
                if args.verbose:
                    print(f"  canary cycle {cycle} [{plan['cls']}] "
                          f"{plan['plan']!r}: chaos healed, "
                          f"co-tenant clean")
            except (AssertionError, subprocess.TimeoutExpired) as e:
                failures.append((plan, e))
                print(f"FAIL canary cycle {cycle} [{plan['cls']}] "
                      f"{plan['plan']!r}: {type(e).__name__}: {e}",
                      file=sys.stderr)
        # the pool itself must have survived every cycle: all 2*N
        # tenants in history, every one rc 0, nothing stuck in queue —
        # and the /status FT timeline must carry one revive per cycle
        # (the errmgr healed IN the server; client IOF never sees it)
        ps = tpurun(["--dvm-ps", "--dvm-uri", uri], timeout=60)
        try:
            table = json.loads(ps.stdout)
            done = [h for h in table.get("history", [])
                    if h.get("rc") == 0]
            expect = 2 * args.plans
            assert len(done) >= min(expect, 20), \
                (f"pool history shows {len(done)} clean jobs, "
                 f"expected {expect}: {ps.stdout[-2000:]}")
            assert table.get("queue_depth", 0) == 0, ps.stdout[-2000:]
            import urllib.request
            with open(uri + ".metrics") as f:
                http = f.read().strip()
            with urllib.request.urlopen(http + "/status",
                                        timeout=10) as resp:
                status = json.loads(resp.read().decode())
            revives = {(e["jobid"], e["rank"])
                       for j in status.get("jobs", [])
                       for e in j.get("ft_events", [])
                       if e["kind"] == "revive"}
            assert len(revives) >= args.plans, \
                (f"{len(revives)} revive events on the FT timeline for "
                 f"{args.plans} chaos cycles: {sorted(revives)}")
        except (ValueError, AssertionError, OSError) as e:
            failures.append(({"cls": "pool-state"}, e))
            print(f"FAIL canary pool-state: {e}", file=sys.stderr)
    finally:
        tpurun(["--dvm-stop", "--dvm-uri", uri], timeout=30)
        try:
            server.wait(timeout=15)
        except subprocess.TimeoutExpired:
            server.kill()

    if failures:
        print(f"chaos_soak --canary: {len(failures)}/{args.plans} "
              f"cycles FAILED", file=sys.stderr)
        return 1
    print(f"chaos_soak --canary: {args.plans}/{args.plans} cycles ok "
          f"(seed {args.seed}, np {np_}, {steps} steps, "
          f"one standing pool)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--plans", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--np", type=int, default=4, dest="np_")
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--replay-every", type=int, default=4,
                    help="replay every Nth plan to assert determinism "
                         "(0 = no replays; default 4 is co-prime with "
                         "the 9-policy rotation so every policy — "
                         "including the drop-carrying notify-shrink "
                         "plans — gets replayed over a long soak)")
    ap.add_argument("--only", default=None, choices=POLICIES,
                    help="run only plans of one class (the CI smoke "
                         "jobs pick single scenarios this way)")
    ap.add_argument("--canary", action="store_true",
                    help="multi-tenant pool mode: one standing selfheal "
                         "DVM serves every cycle; each cycle runs a "
                         "seeded chaos tenant (kill/hang/kill@coll "
                         "rotation) NEXT TO a fault-free canary ring — "
                         "both must exit 0 with exact recomputed accs "
                         "(--plans = cycles)")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--guard", action="store_true",
                    help="preflight: refuse to soak when hours-old "
                    "PPID-1 orphaned ompi_tpu processes poison the box "
                    "(their CPU steal turns timing-sensitive chaos "
                    "windows into flakes)")
    ap.add_argument("--guard-kill", action="store_true",
                    help="like --guard but SIGKILL the orphans and "
                    "proceed")
    args = ap.parse_args(argv)

    if args.guard or args.guard_kill:
        from tools import killorphans

        if not killorphans.preflight("chaos_soak",
                                     kill=args.guard_kill):
            return 2

    if args.canary:
        return run_canary(args)

    failures = []
    plans, i = [], 0
    while len(plans) < args.plans:
        plan = gen_plan(args.seed, i, args.np_, args.steps)
        i += 1
        if args.only and plan["policy"] != args.only:
            continue
        plans.append(plan)
    for i, plan in enumerate(plans):
        log_a = tempfile.mkdtemp(prefix=f"chaos_log_{i}a_")
        try:
            run_plan(plan, args.np_, args.steps, log_a, args.verbose)
            if args.replay_every and i % args.replay_every == 0:
                log_b = tempfile.mkdtemp(prefix=f"chaos_log_{i}b_")
                run_plan(plan, args.np_, args.steps, log_b, False)
                check_replay(plan, read_fault_logs(log_a),
                             read_fault_logs(log_b))
                if args.verbose:
                    print(f"  plan {i:>2} replay: deterministic")
        except (AssertionError, subprocess.TimeoutExpired) as e:
            failures.append((plan, e))
            print(f"FAIL plan {i} [{plan['policy']}] {plan['plan']!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
    total = args.plans
    if failures:
        print(f"chaos_soak: {len(failures)}/{total} plans FAILED",
              file=sys.stderr)
        return 1
    print(f"chaos_soak: {total}/{total} plans ok "
          f"(seed {args.seed}, np {args.np_}, {args.steps} steps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
