"""Benchmark driver — prints ONE JSON line.

Mode is chosen by available hardware:

- **multi-device** (≥2 chips): the north-star metric — MPI_Allreduce busbw
  over ICI (BASELINE.json): float32 allreduce through the framework's
  device path (DeviceCommunicator.allreduce → lax.psum), busbw =
  2·(n-1)/n · bytes / time.
- **single chip**: flagship-model train-step throughput (tokens/s) with
  bfloat16 compute (MXU path) vs the same model in float32 — vs_baseline is
  the bf16/fp32 speedup, since the reference publishes no absolute numbers
  (BASELINE.md: "published: {}").

All diagnostics go to stderr; stdout carries exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_allreduce_busbw(devices) -> dict:
    import jax

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.parallel.mesh import make_mesh

    import jax
    from jax.sharding import PartitionSpec as P

    n = len(devices)
    mesh = make_mesh(devices=devices)
    comm = device_world(mesh)
    per_device = 1 << 28  # 256 MiB per device
    x = np.ones((n * (per_device // 4),), np.float32)

    # build ONE jitted program and reuse it — retracing would dominate
    fn = jax.jit(jax.shard_map(
        lambda s: comm.allreduce(s), mesh=mesh,
        in_specs=P("world"), out_specs=P("world"), check_vma=False))
    jax.block_until_ready(fn(x))  # compile + warm ICI
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    shard_bytes = x.nbytes / n
    busbw = 2 * (n - 1) / n * shard_bytes / dt
    log(f"allreduce {shard_bytes/2**20:.0f}MiB/dev over {n} devices: "
        f"{dt*1e3:.2f}ms → busbw {busbw/2**30:.2f} GiB/s")
    return {
        "metric": f"MPI_Allreduce busbw over ICI ({n} chips, fp32)",
        "value": round(busbw / 2**30, 3),
        "unit": "GiB/s",
        "vs_baseline": 1.0,  # reference publishes no number (BASELINE.md)
    }


def _throughput(cfg, mesh, tokens, steps=8):
    import jax

    from ompi_tpu.models import transformer as tfm

    params = tfm.init_params(cfg)
    step, init_opt = tfm.make_train_step(cfg, mesh, lr=1e-3)
    opt_state = init_opt(params)
    params, opt_state, loss = step(params, opt_state, tokens)  # compile
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, tokens)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / steps
    toks = tokens.size
    return toks / dt, float(loss)


def bench_flagship_single_chip() -> dict:
    import jax

    from ompi_tpu.models.transformer import TransformerConfig
    from ompi_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    base = dict(vocab=32_000, d_model=1024, n_heads=16, n_layers=8,
                d_ff=4096, seq=1024, attention="ring")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, base["vocab"], size=(4, base["seq"])).astype(np.int32)

    bf16, loss_b = _throughput(
        TransformerConfig(**base, compute_dtype="bfloat16"), mesh, tokens)
    log(f"bf16 train step: {bf16:,.0f} tok/s (loss {loss_b:.3f})")
    fp32, loss_f = _throughput(
        TransformerConfig(**base, compute_dtype="float32"), mesh, tokens)
    log(f"fp32 train step: {fp32:,.0f} tok/s (loss {loss_f:.3f})")
    return {
        "metric": "flagship transformer train-step throughput "
                  "(1 chip, bf16, 110M params, seq 1024)",
        "value": round(bf16, 1),
        "unit": "tokens/s",
        "vs_baseline": round(bf16 / fp32, 3),  # speedup over fp32 same model
    }


def main() -> None:
    import jax

    devices = jax.devices()
    log(f"devices: {devices}")
    if len(devices) >= 2:
        result = bench_allreduce_busbw(devices)
    else:
        result = bench_flagship_single_chip()
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
