"""Benchmark driver — prints ONE JSON line on stdout, always.

Crash-proof by construction (round-1 failure mode: ``jax.devices()`` raised
when the TPU tunnel was down and the traceback landed on stdout):

- The accelerator backend is probed in a **subprocess with a timeout**; if
  it is unreachable the bench re-points jax at a virtual 8-device CPU
  platform and still produces a valid JSON record (tagged ``"backend"``).
- Everything runs under a top-level try/except that emits a JSON error
  record rather than a traceback.

Primary metric:

- **multi-device** (≥2 chips): MPI_Allreduce busbw over ICI (BASELINE.json
  north star) — float32 allreduce through the device path
  (DeviceCommunicator.allreduce → lax.psum), busbw = 2·(n-1)/n·bytes/time.
- **single chip**: flagship-model **MFU** — model FLOPs/step ÷ step time ÷
  chip peak FLOPs (bf16). ``vs_baseline`` is MFU as a fraction of the 40%
  MFU a well-tuned reference-class training stack reaches on this hardware
  class; tokens/s is carried alongside.

The full BASELINE.md config matrix (ring p50, 2D-mesh bcast/allgather,
7B-param reduce_scatter+allgather gradient harness, oshmem max-reduction /
circular-shift on the device path) runs after the primary metric; every
config emits a JSON row into ``BENCH_MATRIX.json`` even on 1 chip, with
per-row error capture.

All diagnostics go to stderr; stdout carries exactly one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Escalating per-attempt budgets (round-3 failure: ONE 150s shot hit a slow
# TPU-runtime init and the whole round's perf evidence fell back to CPU).
# Total worst case ≈ 90+150+240 + 2×30s pause ≈ 9 min — still bounded, but a
# transiently slow tunnel init now gets three chances to come up.
_PROBE_BUDGETS_S = tuple(
    int(x) for x in os.environ.get("OMPI_TPU_BENCH_PROBE_BUDGETS",
                                   "90,150,240").split(",")
    if x.strip()) or (90, 150, 240)
_PROBE_PAUSE_S = int(os.environ.get("OMPI_TPU_BENCH_PROBE_PAUSE", "30"))
# Recovery window (round-4 failure: the escalating budgets total ~9 min,
# but the observed tunnel outages last hours; 8.5 min of retries cannot
# outlast them).  Round-5 inversion: the CPU-fallback matrix runs FIRST
# and recovery probes spend only the budget that remains — a driver
# SIGTERM mid-recovery then kills a run whose record already carries the
# full matrix, instead of one that spent its whole life probing
# (VERDICT r5 "Next round" #2).  0 disables (tests / interactive runs).
_RECOVERY_WINDOW_S = int(os.environ.get(
    "OMPI_TPU_BENCH_RECOVERY_WINDOW", "2700"))
# Total wall-clock the DRIVER allows the whole bench run (seconds); 0 =
# unknown.  When set, the recovery window is sized to what is left of it
# (minus a margin to emit the record) so the driver's kill never lands
# mid-probe before the record is complete.
_DRIVER_BUDGET_S = int(os.environ.get("BENCH_DRIVER_BUDGET_S", "0"))
_DRIVER_MARGIN_S = 60
_RECOVERY_PROBE_BUDGET_S = int(os.environ.get(
    "OMPI_TPU_BENCH_RECOVERY_BUDGET", "420"))
_RECOVERY_PAUSE_S = int(os.environ.get(
    "OMPI_TPU_BENCH_RECOVERY_PAUSE", "120"))
_MATRIX_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_MATRIX.json")

# Persistent XLA compilation cache, shared across bench/sweep runs on
# this host: the round-3/4 failure mode is the tunnel's remote compile
# helper stalling for many minutes on the flagship program — once any
# run has compiled it, every later run (including the driver's
# end-of-round bench) should hit the disk cache instead of recompiling.
_CACHE_DIR = os.environ.get(
    "OMPI_TPU_JAX_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))


def _enable_compile_cache() -> None:
    # env form so every subprocess (probe, harness ranks) inherits it; a
    # pre-set JAX_COMPILATION_CACHE_DIR wins and the parent follows it
    # (parent and children MUST share one cache or the stall-avoidance
    # this exists for does nothing)
    cache = os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)
    try:
        os.makedirs(cache, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache)
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        log(f"compile cache unavailable: {e}")

# Peak dense bf16 FLOP/s by device kind (public figures); cpu has no
# meaningful peak → MFU reported as 0 and flagged.
_PEAK_FLOPS = [
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _tail(s, n: int = 300) -> str:
    if isinstance(s, bytes):
        s = s.decode("utf-8", errors="replace")
    return (s or "")[-n:]


def _probe_backend() -> tuple[dict | None, list[dict]]:
    """Ask a subprocess what jax.devices() sees; retry with escalating
    budgets before giving up.

    Returns ({"n", "platform", "kind"} | None, per-attempt diagnostics).
    The diagnostics ride into the final JSON record so a CPU fallback is
    distinguishable after the fact: "timeout" = runtime init hung (tunnel
    alive but slow — round 3's failure), nonzero rc = init actively
    failed (tunnel down).  One shot cost round 3 its entire TPU evidence;
    retries are cheap next to that.

    This is ONLY the escalating initial attempts: on failure the caller
    banks the CPU-fallback evidence first and then spends whatever budget
    remains in :func:`_probe_recovery` (round-5 inversion — probing must
    never again starve the matrix out of the record).
    """
    attempts: list[dict] = []
    _partial["probe_attempts"] = attempts   # live view for the
    # terminal-signal record (list mutated in place below)
    for i, budget in enumerate(_PROBE_BUDGETS_S):
        rec = _probe_once(i + 1, budget)
        attempts.append(rec)
        if rec["outcome"] == "ok":
            return rec.pop("probe"), attempts
        if i + 1 < len(_PROBE_BUDGETS_S):
            log(f"pausing {_PROBE_PAUSE_S}s before probe retry")
            time.sleep(_PROBE_PAUSE_S)
    return None, attempts


def _recovery_window_s(elapsed_s: float) -> int:
    """Seconds the recovery probes may spend, AFTER the CPU evidence is
    banked: the configured window, clipped to what is left of the
    driver's total allowance (``BENCH_DRIVER_BUDGET_S``) minus a margin
    to emit the record."""
    window = _RECOVERY_WINDOW_S
    if _DRIVER_BUDGET_S > 0:
        remaining = _DRIVER_BUDGET_S - elapsed_s - _DRIVER_MARGIN_S
        window = max(0, min(window, int(remaining)))
    return window


def _probe_recovery(attempts: list[dict],
                    window_s: int) -> dict | None:
    """Bounded late-recovery probing.  The observed failure mode is a
    multi-hour tunnel outage; a transient one may still end within the
    bench run.  Keep probing with long budgets over ``window_s`` so the
    record proves the tunnel revived (or stayed down the whole window).
    Appends to ``attempts`` in place; returns the probe dict on revival.
    """
    if window_s <= 0:
        return None
    deadline = time.monotonic() + window_s
    log(f"entering recovery window: {window_s}s of "
        f"{_RECOVERY_PROBE_BUDGET_S}s-budget probes every "
        f"{_RECOVERY_PAUSE_S}s")
    while time.monotonic() < deadline:
        remaining = deadline - time.monotonic()
        # probe-budget floor: 60s keeps probes meaningful on an unknown
        # allowance, but with a driver budget the window edge is hard —
        # a floored probe would overrun into the record-emission margin
        floor = 60 if _DRIVER_BUDGET_S <= 0 else 1
        budget = int(min(_RECOVERY_PROBE_BUDGET_S, max(floor, remaining)))
        rec = _probe_once(len(attempts) + 1, budget)
        rec["recovery_window"] = True
        attempts.append(rec)
        if rec["outcome"] == "ok":
            return rec.pop("probe")
        if time.monotonic() + _RECOVERY_PAUSE_S < deadline:
            time.sleep(_RECOVERY_PAUSE_S)
        else:
            break
    log("recovery window exhausted")
    return None


def _probe_once(attempt_no: int, budget: int) -> dict:
    """One subprocess backend probe.  Returns a diagnostic record; on
    success it carries the parsed probe dict under ``"probe"`` and
    ``outcome == "ok"``."""
    code = ("import jax, json; ds = jax.devices(); "
            "print(json.dumps({'n': len(ds), 'platform': ds[0].platform, "
            "'kind': ds[0].device_kind}))")
    t0 = time.perf_counter()
    rec: dict = {"attempt": attempt_no, "budget_s": budget,
                 "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=budget)
    except subprocess.TimeoutExpired as e:
        rec.update(outcome="timeout (runtime init hung)",
                   stderr_tail=_tail(e.stderr))
        log(f"backend probe attempt {attempt_no} timed out after {budget}s")
        return rec
    rec["wall_s"] = round(time.perf_counter() - t0, 1)
    if out.returncode != 0:
        rec.update(outcome=f"rc={out.returncode} (init failed)",
                   stderr_tail=_tail(out.stderr))
        log(f"backend probe attempt {attempt_no} failed "
            f"rc={out.returncode}: {_tail(out.stderr, 500)}")
        return rec
    try:
        probe = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001
        rec.update(outcome=f"unparseable ({e})",
                   stderr_tail=_tail(out.stdout, 200))
        log(f"backend probe unparseable ({e}): {_tail(out.stdout, 200)}")
        return rec
    rec.update(outcome="ok", probe=probe)
    return rec


def _force_cpu(n: int = 8) -> None:
    """Re-point jax at a virtual n-device CPU platform.

    Must go through ``jax.config`` (not env vars): the ambient site
    customization re-registers the accelerator plugin and updates
    ``jax_platforms`` at interpreter startup, which beats JAX_PLATFORMS
    from the environment.  A config update after import wins.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses we spawn
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", n)


def _peak_flops(kind: str) -> float | None:
    k = kind.lower()
    for needle, peak in _PEAK_FLOPS:
        if needle in k:
            return peak
    return None


# ---------------------------------------------------------------------------
# primary metrics
# ---------------------------------------------------------------------------

def bench_allreduce_busbw(devices) -> dict:
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.parallel.mesh import make_mesh

    n = len(devices)
    mesh = make_mesh(devices=devices)
    comm = device_world(mesh)
    # 256 MiB per device on hardware; small on host-platform devices
    # (virtual CPU "chips" share one core — full size takes minutes)
    per_device = (1 << 28) if devices[0].platform == "tpu" else (1 << 22)
    x = _device_put(np.ones((n * (per_device // 4),), np.float32),
                    mesh, P("world"))

    # the allreduce runs INSIDE one compiled program (fori_loop over the
    # shard_map'd body, rescaled by 1/n so the carry stays finite) and
    # per-iter cost comes from the two-point slope — on the tunnel a
    # python-side dispatch loop times the ~1.5s round trip, not ICI
    scale = np.float32(1.0 / n)

    make = _loop_maker(lambda s: comm.allreduce(s) * scale, mesh,
                       P("world"), P("world"))
    shard_bytes = x.nbytes / n
    row = {
        "metric": f"MPI_Allreduce busbw over ICI ({n} chips, fp32)",
        "unit": "GiB/s",
        "vs_baseline": 1.0,  # reference publishes no number (BASELINE.md)
    }
    if n == 1:
        fn = make(1)
        jax.block_until_ready(fn(x))
        t0 = time.perf_counter()
        _ = float(jax.device_get(fn(x).ravel()[0]))
        dt = time.perf_counter() - t0
        row.update(value=0.0, dispatch_ms=round(dt * 1e3, 1),
                   note=_ONE_CHIP_NOTE)
        log(f"allreduce: {_ONE_CHIP_NOTE} ({dt*1e3:.0f}ms dispatch)")
        return row
    dt, extra = _slope_or_bound(make, x, *_loop_iters(devices))
    busbw = 2 * (n - 1) / n * shard_bytes / dt
    log(f"allreduce {shard_bytes/2**20:.0f}MiB/dev over {n} devices: "
        f"{dt*1e3:.2f}ms/iter (slope) → busbw {busbw/2**30:.2f} GiB/s")
    row.update(value=round(busbw / 2**30, 3),
               iter_ms=round(dt * 1e3, 2), **extra)
    return row


def _device_put(x, mesh, spec):
    """Place a host array on the mesh BEFORE any timing loop — feeding
    numpy into a jitted fn pays a full H2D transfer per call, which
    swamps the collective being measured (round-2 verdict: the matrix
    reported 0.07 GiB/s on hardware that moves ~800)."""
    import jax
    from jax.sharding import NamedSharding

    return jax.device_put(x, NamedSharding(mesh, spec))


def _slope_time(make_fn, x, lo: int, hi: int, reps: int = 2):
    """Per-iteration seconds of an in-jit loop body via the two-point
    method the matmul_peak calibration validated (176 TF/s measured
    through a tunnel whose per-dispatch round trip is ~1.5s): build the
    SAME program at two ``fori_loop`` trip counts, time one dispatch of
    each with a 1-element value readback as the fence, and take the
    slope — every per-dispatch constant (tunnel RT, dispatch, readback)
    cancels.  ``make_fn(iters)`` must return a jitted callable whose
    output matches ``x``'s shape/sharding (a well-formed loop carry).

    Only meaningful when the loop body does real per-iteration work: a
    single-chip "collective" is the identity, XLA folds the whole loop
    away, and the slope is noise — callers keep single-dispatch timing
    for that case.
    """
    import jax

    f_lo, f_hi = make_fn(lo), make_fn(hi)

    def timed(f):
        out = f(x)
        _ = float(jax.device_get(out.ravel()[0]))  # compile + warm + fence
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = f(x)
            _ = float(jax.device_get(out.ravel()[0]))
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo, t_hi = timed(f_lo), timed(f_hi)
    slope = (t_hi - t_lo) / (hi - lo)
    if slope <= 0 or (t_hi - t_lo) < 0.02 * t_lo:
        # collapsed slope: the extra iterations vanished into timing
        # noise (host contention, or the body optimized away).  Report
        # the honest upper bound — one dispatch amortized over its trip
        # count — rather than a nonsense near-zero per-iter cost.
        return None, t_lo, t_hi
    return slope, t_lo, t_hi


_SLOPE_COLLAPSED = ("two-point slope collapsed under timing noise; per-iter "
                    "cost is an upper bound (one dispatch / trip count, "
                    "dispatch overhead included)")


def _loop_maker(kernel, mesh, in_specs, out_specs):
    """make(iters) factory for the slope rows: ONE compiled program
    running ``iters`` trips of the shard_map'd kernel (carry must keep
    the input's shape/sharding)."""
    import jax

    def make(iters):
        body = jax.shard_map(kernel, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, iters, lambda i, y: body(y), a))

    return make


def _slope_fields(t_lo: float, t_hi: float, lo: int, hi: int):
    """The shared slope-or-bound POLICY: per-iter seconds + row fields
    from two wall times.  Collapse threshold and the suspect contract
    live here only — both the loop-carry rows (via _slope_or_bound) and
    rows with other call signatures (decode) decide through this."""
    extra = {"wall_lo_s": round(t_lo, 3), "wall_hi_s": round(t_hi, 3)}
    dt = (t_hi - t_lo) / (hi - lo)
    if dt <= 0 or (t_hi - t_lo) < 0.02 * t_lo:
        extra["suspect"] = _SLOPE_COLLAPSED
        return t_hi / hi, extra
    return dt, extra


def _slope_or_bound(make_fn, x, lo: int, hi: int):
    """(per-iter seconds, extra-row-fields) — slope when clean, else the
    t_hi/hi upper bound with a ``suspect`` note."""
    _dt, t_lo, t_hi = _slope_time(make_fn, x, lo, hi)
    return _slope_fields(t_lo, t_hi, lo, hi)


def _loop_iters(devices) -> tuple[int, int]:
    """(lo, hi) trip counts: generous on TPU where per-iter work is
    fast; small on the CPU fallback where a 256MiB collective costs
    ~0.5s/iter of host memcpy."""
    return (4, 20) if devices[0].platform == "tpu" else (2, 6)


_ONE_CHIP_NOTE = ("single device — the collective degenerates to identity; "
                  "busbw is defined over ICI (needs >=2 chips), this row "
                  "times dispatch only; the hbm_copy row carries the "
                  "honest single-chip memory-bandwidth record")


# Any device-path row below this on real TPU measures overhead, not the
# data plane (HBM ~800 GiB/s, single-chip "collectives" are copies).
_DEVICE_ROW_FLOOR_GIBPS = 10.0


def _flag_suspect(row: dict, backend: str) -> dict:
    if (backend == "tpu" and row.get("unit") == "GiB/s"
            and row.get("value", 0) < _DEVICE_ROW_FLOOR_GIBPS):
        row["suspect"] = ("below sanity floor "
                          f"({_DEVICE_ROW_FLOOR_GIBPS} GiB/s): likely "
                          "measuring dispatch/transfer, not the data plane")
    return row


def _count_params(params) -> int:
    import jax

    return int(sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(params)))


def _time_train_loop(cfg, mesh, tokens, chain: int, outer: int):
    """Time `outer` dispatches of a `chain`-step compiled train loop.

    All state lives on device (params/opt donated and fed back — feeding
    numpy in would time the H2D transfer, round-2 weak #3) and the clock
    is closed by a VALUE readback: on remote/tunneled runtimes
    ``block_until_ready`` can return before the device work completes, so
    only fetching a result truly fences (round-2's 3% "MFU" was partly
    this artifact in reverse — per-step dispatch stalls).
    """
    import jax

    from ompi_tpu.models import transformer as tfm

    params = jax.device_put(tfm.init_params(cfg))
    n_params = _count_params(params)
    loop, init_opt = tfm.make_train_loop(cfg, mesh, lr=1e-3, steps=chain)
    opt_state = jax.device_put(init_opt(params))
    tokens = jax.device_put(tokens)
    params, opt_state, losses = loop(params, opt_state, tokens)  # compile
    _ = float(losses[-1])                                        # full sync
    t0 = time.perf_counter()
    for _ in range(outer):
        params, opt_state, losses = loop(params, opt_state, tokens)
    loss = float(losses[-1])                                     # fences all
    dt = (time.perf_counter() - t0) / (outer * chain)
    return dt, n_params, loss


def bench_flagship_mfu(kind: str) -> dict:
    """Single-chip flagship train step → MFU (PaLM-style accounting:
    6·N FLOPs/token for the dense path + 12·L·D·S for attention)."""
    import jax

    from ompi_tpu.models.transformer import TransformerConfig
    from ompi_tpu.parallel.mesh import make_mesh

    on_cpu = jax.devices()[0].platform == "cpu"
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=jax.devices()[:1])
    # flagship: 468M params, head_dim 128.  Config picked by the measured
    # v5e sweep (MFU_SWEEP.jsonl): at seq 1024 plain XLA dot-product
    # attention beats the pallas flash kernel (723 vs 963 ms/step —
    # attention is ~7% of FLOPs here and XLA's fused softmax wins; the
    # flash kernel + ring remain the long-context sp>1 path), ce_chunk
    # 256 beats 128/512, and a 32-step in-jit chain amortizes the ~1.5s
    # tunnel dispatch round-trip measured by the matmul_peak row.
    base = dict(vocab=32_000, d_model=2048, n_heads=16, n_layers=8,
                d_ff=8192, seq=1024, attention="xla",
                # chunked CE: drops the (B,T,V) f32 logits+log-softmax
                # pair (~4 GiB at batch 16) to O(chunk·V) — parity-tested
                # vs the full path (test_chunked_ce_matches_full)
                ce_chunk=256)
    batch, chain, outer = 16, 32, 1
    if on_cpu:  # fallback mode: keep the gate fast; MFU is 0 here anyway
        base.update(d_model=256, n_heads=8, n_layers=2, d_ff=1024, seq=256)
        batch, chain, outer = 2, 2, 1
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, base["vocab"],
                          size=(batch, base["seq"])).astype(np.int32)

    dt, n_params, loss = _time_train_loop(
        TransformerConfig(**base, compute_dtype="bfloat16", remat="dots"),
        mesh, tokens, chain, outer)
    n_tokens = tokens.size
    flops_per_token = 6 * n_params + 12 * base["n_layers"] * base["d_model"] * base["seq"]
    model_flops = flops_per_token * n_tokens
    toks_per_s = n_tokens / dt
    peak = _peak_flops(kind)
    mfu = (model_flops / dt / peak) if peak else 0.0
    log(f"bf16 train step: {dt*1e3:.1f}ms, {toks_per_s:,.0f} tok/s, "
        f"{n_params/1e6:.0f}M params, model {model_flops/1e9:.1f} GFLOP/step, "
        f"peak={peak}, MFU={mfu*100:.1f}% (loss {loss:.3f})")
    return {
        "metric": f"flagship transformer train-step MFU (1 chip {kind}, "
                  f"bf16, {n_params/1e6:.0f}M params, seq {base['seq']})",
        "value": round(mfu * 100, 2),
        "unit": "% MFU",
        # no reference number published (BASELINE.md); 40% MFU is the
        # well-tuned-training-stack bar on this hardware class
        "vs_baseline": round(mfu / 0.40, 3) if peak else 0.0,
        "tokens_per_s": round(toks_per_s, 1),
        "step_ms": round(dt * 1e3, 2),
        "params": n_params,
    }


# ---------------------------------------------------------------------------
# BASELINE.md config matrix → BENCH_MATRIX.json
# ---------------------------------------------------------------------------

def matrix_ring_latency() -> dict:
    """Config 1: 4-rank send/recv ring (host path, real sockets), p50 lap."""
    from tests.mpi.harness import run_ranks

    laps = 200
    msg = np.array([0], np.int32)

    def ring(comm):
        rank, size = comm.rank, comm.size
        nxt, prv = (rank + 1) % size, (rank - 1) % size
        times = []
        for i in range(20 + laps):
            if rank == 0:
                t0 = time.perf_counter()
                comm.send(msg, dest=nxt, tag=1)
                comm.recv(source=prv, tag=1)
                if i >= 20:
                    times.append(time.perf_counter() - t0)
            else:
                m = comm.recv(source=prv, tag=1)
                comm.send(m, dest=nxt, tag=1)
        return times

    results = run_ranks(4, ring, timeout=120.0)
    p50 = float(np.percentile(np.array(results[0]) * 1e6, 50))
    return {
        "metric": "ring_c 4-rank lap latency p50 (host path)",
        "value": round(p50, 1), "unit": "us", "vs_baseline": 1.0,
        "per_hop_us": round(p50 / 4, 2),
    }


def matrix_allreduce_sweep(devices) -> dict:
    """Config 2: OSU-style MPI_Allreduce size sweep — the device path
    (coll/xla → psum) per size, with the host path (coll/tuned algorithms
    over in-process ranks) alongside for the crossover picture."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.parallel.mesh import make_mesh

    n = len(devices)
    mesh = make_mesh(devices=devices)
    comm = device_world(mesh)
    dev_rows = {}
    scale = np.float32(1.0 / n)
    sizes = (("4KiB", 1024), ("1MiB", 1 << 18), ("64MiB", 1 << 24))
    if n == 1:
        for label, _elems in sizes:
            dev_rows[label] = {"us": None, "note": _ONE_CHIP_NOTE}
        sizes = ()
    for label, elems in sizes:
        x = _device_put(np.ones((n * elems,), np.float32), mesh, P("world"))
        make = _loop_maker(lambda s: comm.allreduce(s) * scale, mesh,
                           P("world"), P("world"))
        lo, hi = _loop_iters(devices)
        if elems <= (1 << 18):  # small payloads: longer loops, less noise
            lo, hi = lo * 4, hi * 4
        dt, extra = _slope_or_bound(make, x, lo, hi)
        shard = elems * 4
        dev_rows[label] = {
            "us": round(dt * 1e6, 1),
            "busbw_gibps": round(2 * (n - 1) / n * shard / dt / 2**30, 3),
        }
        if "suspect" in extra:
            dev_rows[label]["suspect"] = extra["suspect"]

    # host path: 4 in-process ranks through coll/tuned's decision layer
    from tests.mpi.harness import run_ranks

    host_rows = {}
    for label, elems in (("4B", 1), ("4KiB", 1024), ("1MiB", 1 << 18)):
        payload = np.ones(elems, np.float32)
        iters = 30 if elems <= 1024 else 10

        def body(comm_):
            import time as _t

            comm_.allreduce(payload)          # warm routes
            t0 = _t.perf_counter()
            for _ in range(iters):
                comm_.allreduce(payload)
            return (_t.perf_counter() - t0) / iters

        dts = run_ranks(4, body, timeout=120.0)
        dt = max(dts)
        host_rows[label] = {"us": round(dt * 1e6, 1)}

    return {
        "metric": f"MPI_Allreduce sweep ({n} dev psum | 4-rank host tuned)",
        "value": dev_rows["64MiB"].get("busbw_gibps", 0.0), "unit": "GiB/s",
        "vs_baseline": 1.0,
        "device_path": dev_rows, "host_path_4rank": host_rows,
    }


def matrix_mesh_bcast_allgather(devices) -> dict:
    """Config 3: Bcast + Allgather over a 2D mesh, mixed dtypes."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import DeviceCommunicator
    from ompi_tpu.parallel.mesh import make_mesh, mesh_shape_for

    n = len(devices)
    shape = mesh_shape_for(n, ["x", "y"])
    mesh = make_mesh(shape, devices=devices)
    comm = DeviceCommunicator(mesh, ("x", "y"))
    if n == 1:
        return {
            "metric": f"Bcast+Allgather 2D mesh {tuple(shape.values())}, "
                      "mixed dtypes",
            "value": 0.0, "unit": "GiB/s", "vs_baseline": 1.0,
            "note": _ONE_CHIP_NOTE,
        }
    nbytes = 0
    total_dt = 0.0
    suspect = None
    for dtype in (np.float32, np.bfloat16 if hasattr(np, "bfloat16")
                  else np.float16, np.int32):
        x = _device_put(
            np.ones((n * (1 << 22),), dtype=np.float32).astype(dtype),
            mesh, P(("x", "y")))
        shard_elems = x.shape[0] // n

        def kernel(s):
            # bcast + allgather, then slice this device's shard back out
            # so the loop carry keeps the input's shape/sharding
            b = comm.bcast(s, root=0)
            full = comm.allgather(b)
            return jax.lax.dynamic_slice_in_dim(
                full, comm.rank() * shard_elems, shard_elems)

        make = _loop_maker(kernel, mesh, P(("x", "y")), P(("x", "y")))
        dt, extra = _slope_or_bound(make, x, *_loop_iters(devices))
        total_dt += dt
        nbytes += x.nbytes
        if "suspect" in extra:
            suspect = extra["suspect"]
    gbps = nbytes / total_dt / 2**30
    row = {
        "metric": f"Bcast+Allgather 2D mesh {tuple(shape.values())}, "
                  "mixed dtypes",
        "value": round(gbps, 3), "unit": "GiB/s", "vs_baseline": 1.0,
    }
    if suspect:
        row["suspect"] = suspect
    return row


def matrix_hbm_copy(devices) -> dict:
    """HBM-bandwidth calibration (the memory-side twin of matmul_peak's
    MXU row): slope-timed read+write sweep of one device's HBM.  This is
    the sanity floor for every bandwidth row — a single-chip self-put or
    degenerate collective can never beat it, and on one chip it is the
    honest 'what the memory system can do' record the n=1 matrix rows
    point at instead of timing dispatch."""
    import jax

    n_elems = (1 << 26) if devices[0].platform == "tpu" else (1 << 22)
    x = jax.device_put(np.ones((n_elems,), np.float32), devices[0])
    nbytes = x.nbytes

    def make(iters):
        return jax.jit(lambda a: jax.lax.fori_loop(
            0, iters, lambda i, y: y + np.float32(1.0), a))

    lo, hi = (8, 72) if devices[0].platform == "tpu" else (2, 10)
    dt, extra = _slope_or_bound(make, x, lo, hi)
    # each iteration reads the buffer and writes it back
    gbps = 2 * nbytes / dt / 2**30
    return {
        "metric": f"HBM read+write bandwidth ({nbytes >> 20}MiB fp32, "
                  f"1 device)",
        "value": round(gbps, 2), "unit": "GiB/s", "vs_baseline": 1.0,
        "per_iter_ms": round(dt * 1e3, 3), **extra,
    }


def matrix_grad_reduce_scatter(devices) -> dict:
    """Config 4: data-parallel gradient reduce_scatter + allgather on
    float32 buffers, sized to HBM (7B params when it fits)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.parallel.mesh import make_mesh

    n = len(devices)
    if devices[0].platform == "cpu":
        limit = 128 << 20  # virtual cpu devices share host RAM — stay small
    else:
        try:
            limit = devices[0].memory_stats()["bytes_limit"]
        except Exception:  # noqa: BLE001 — backend without memory_stats
            limit = 8 << 30
    # grad shard + scattered output + slack must fit per device
    params = min(7_000_000_000, int(limit * 0.15 / 4) * n)
    params -= params % (n * 1024)
    mesh = make_mesh(devices=devices)
    x = _device_put(np.ones((params,), np.float32), mesh, P("world"))
    nbytes = x.nbytes

    scale = np.float32(1.0 / n)

    def kernel(s):
        scattered = jax.lax.psum_scatter(s, "world", tiled=True) * scale
        return jax.lax.all_gather(scattered, "world", tiled=True)

    make = _loop_maker(kernel, mesh, P("world"), P("world"))
    row = {
        "metric": f"grad reduce_scatter+allgather ({params/1e9:.2f}B fp32 "
                  f"params, {n} dev)",
        "unit": "GiB/s", "vs_baseline": 1.0, "params": params,
    }
    if n == 1:
        row.update(value=0.0, note=_ONE_CHIP_NOTE)
        return row
    dt, extra = _slope_or_bound(make, x, *_loop_iters(devices))
    gbps = 2 * nbytes / dt / 2**30  # RS + AG each move ~the buffer once
    row.update(value=round(gbps, 3), step_ms=round(dt * 1e3, 2), **extra)
    return row


def matrix_oshmem_device(devices) -> dict:
    """Config 5: oshmem max-reduction + circular shift on the device path
    (symmetric-heap semantics: every device holds an identically-shaped
    shard; max_to_all = pmax, circular shift = ppermute)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.mpi.op import MAX
    from ompi_tpu.parallel.mesh import make_mesh

    n = len(devices)
    mesh = make_mesh(devices=devices)
    comm = device_world(mesh)
    x = _device_put(np.arange(n * (1 << 22), dtype=np.float32),
                    mesh, P("world"))
    nbytes = x.nbytes

    def kernel(s):
        m = comm.allreduce(s, MAX)       # shmem_float_max_to_all
        return comm.shift(m, 1, axis="world")  # circular shift, 1 ICI hop

    make = _loop_maker(kernel, mesh, P("world"), P("world"))
    row = {
        "metric": f"oshmem max_to_all + circular shift ({n} dev, "
                  f"{nbytes/n/2**20:.0f}MiB/dev)",
        "unit": "GiB/s", "vs_baseline": 1.0,
    }
    if n == 1:
        row.update(value=0.0, note=_ONE_CHIP_NOTE)
        return row
    dt, extra = _slope_or_bound(make, x, *_loop_iters(devices))
    row.update(value=round(nbytes / dt / 2**30, 3), **extra)
    return row


def matrix_shm_pingpong() -> dict:
    """Two real PROCESSES ping-ponging raw frames over the shm BTL rings
    — the deployment-shape same-host data-plane number (the reference's
    vader BTL benchmark shape), exercising the fused native frame engine
    (fastdss.ring_send/ring_recv) without GIL sharing between ranks."""
    import multiprocessing as mp

    def child(c2p, p2c, result_q):
        from ompi_tpu.mpi.btl_shm import ShmBTL

        frames = []
        btl = ShmBTL(1, lambda p, h, b: frames.append((h, b)))
        c2p.put(btl.address)
        peer_card = p2c.get()
        btl.connect(0, peer_card)
        # echo every frame back until the stop marker
        seen = 0
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            if len(frames) > seen:
                h, b = frames[seen]
                if h.get("t") == "stop":
                    break
                seen += 1
                btl.send(0, h, b)
            else:
                time.sleep(0)
        result_q.put(seen)
        btl.close()

    from ompi_tpu.mpi.btl_shm import ShmBTL

    ctx = mp.get_context("fork")
    c2p, p2c, result_q = ctx.Queue(), ctx.Queue(), ctx.Queue()
    proc = ctx.Process(target=child, args=(c2p, p2c, result_q),
                       daemon=True)
    proc.start()
    frames = []
    btl = ShmBTL(0, lambda p, h, b: frames.append((h, b)))
    peer_card = c2p.get(timeout=30)
    p2c.put(btl.address)
    btl.connect(1, peer_card)
    hdr = {"t": "eager", "tag": 1, "cid": 0, "seq": 0, "dt": "<i4",
           "elems": 16, "shp": [16]}
    payload = b"\x01" * 64
    laps = []
    warm, iters = 50, 400
    for i in range(warm + iters):
        target = len(frames) + 1   # BEFORE the send: the echo can land
        t0 = time.perf_counter()    # before this line otherwise
        btl.send(1, hdr, payload)
        deadline = t0 + 10
        while len(frames) < target and time.perf_counter() < deadline:
            time.sleep(0)   # yield: the poller thread appends frames
        if i >= warm:
            laps.append(time.perf_counter() - t0)
    btl.send(1, {"t": "stop"}, b"")
    echoed = result_q.get(timeout=30)
    proc.join(timeout=10)
    btl.close()
    p50 = float(np.percentile(np.array(laps) * 1e6, 50))
    return {
        "metric": "shm BTL 2-process ping-pong p50 (64B frames, fused "
                  "native ring)",
        "value": round(p50, 2), "unit": "us", "vs_baseline": 1.0,
        "one_way_us": round(p50 / 2, 2), "echoed": echoed,
    }


def matrix_shm_msgrate() -> dict:
    """Two real PROCESSES, PML-level small-message rate over the shm BTL
    — total CPU work per message (send prologue + C ring publish + fused
    drain + match + deliver).  On small hosts this is the honest
    same-host data-plane number: ping-pong latency there measures the
    scheduler, not the stack (1 core ⇒ every hop is a context switch)."""
    import multiprocessing as mp

    n_msgs = 20_000

    def child(c2p, p2c):
        from ompi_tpu.mpi.comm import Communicator
        from ompi_tpu.mpi.group import Group
        from ompi_tpu.mpi.pml import PmlOb1

        pml = PmlOb1(1)
        c2p.put(pml.address)
        peers = p2c.get()
        pml.set_peers(peers)
        comm = Communicator(Group(range(2)), cid=0, pml=pml,
                            my_world_rank=1)
        buf = np.zeros(16, np.int32)
        for _ in range(n_msgs):
            comm.recv(buf=buf, source=0, tag=1)
        comm.send(buf, dest=0, tag=2)   # ack closes the clock
        pml.close()

    from ompi_tpu.mpi.comm import Communicator
    from ompi_tpu.mpi.group import Group
    from ompi_tpu.mpi.pml import PmlOb1

    ctx = mp.get_context("fork")
    c2p, p2c = ctx.Queue(), ctx.Queue()
    proc = ctx.Process(target=child, args=(c2p, p2c), daemon=True)
    proc.start()
    pml = PmlOb1(0)
    try:
        peers = {0: pml.address, 1: c2p.get(timeout=30)}
        p2c.put(peers)
        pml.set_peers(peers)
        comm = Communicator(Group(range(2)), cid=0, pml=pml,
                            my_world_rank=0)
        msg = np.arange(16, dtype=np.int32)
        comm.send(msg, dest=1, tag=1)   # warm the route + ring
        t0 = time.perf_counter()
        for _ in range(n_msgs - 1):
            comm.send(msg, dest=1, tag=1)
        comm.recv(source=1, tag=2)
        dt = time.perf_counter() - t0
        proc.join(timeout=10)
    finally:
        pml.close()
    return {
        "metric": "shm PML 2-process message rate (64B, fused native "
                  "engine)",
        "value": round(n_msgs / dt),
        "unit": "msg/s", "vs_baseline": 1.0,
        "us_per_msg": round(dt / n_msgs * 1e6, 2),
        "n_cores": os.cpu_count(),
    }


def matrix_remote_dma(devices) -> dict:
    """One-sided put (pallas remote DMA, ≈ btl_put) — on ≥2 chips a true
    cross-chip put timing the single ICI path; on 1 chip the self-put
    degenerate form, which still exercises the kernel's TPU lowering
    (the smoke test VERDICT r3 item 3 asked for)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from ompi_tpu.ops.remote_dma import window_put
    from ompi_tpu.parallel.mesh import make_mesh

    n = len(devices)
    mesh = make_mesh(devices=devices)
    # 64 MiB shards on real hardware; tiny in the CPU interpret mode
    # (the DMA interpreter simulates every transfer — full size would
    # take minutes and measure the simulator, not the data plane)
    elems = (1 << 24) if devices[0].platform == "tpu" else (1 << 13)
    win = _device_put(np.zeros((n * elems,), np.float32), mesh, P("world"))
    val = _device_put(np.ones((n * elems,), np.float32), mesh, P("world"))
    src, dst = (0, 1) if n >= 2 else (0, 0)

    def body(w, v):
        return window_put(w, v, src=src, dst=dst, axis="world")

    sm = jax.shard_map(body, mesh=mesh,
                       in_specs=(P("world"), P("world")),
                       out_specs=P("world"), check_vma=False)

    # the put repeats INSIDE one compiled program; the two-point slope
    # cancels the tunnel dispatch round trip.  Unlike the collective
    # rows this is real per-iteration work even on 1 chip (the self-put
    # is an HBM copy into the window's dst shard), so the slope method
    # applies at any n.
    def make(iters):
        return jax.jit(lambda w: jax.lax.fori_loop(
            0, iters, lambda i, y: sm(y, val), w))

    lo, hi = _loop_iters(devices)
    dt, rdma_extra = _slope_or_bound(make, win, lo, hi)
    out = make(1)(win)
    nbytes = elems * 4
    ok = bool(np.asarray(out[dst * elems: dst * elems + 3] == 1.0).all())
    return {
        "metric": (f"one-sided put "
                   f"{f'{nbytes >> 20}MiB' if nbytes >= 1 << 20 else f'{nbytes >> 10}KiB'} "
                   f"{'chip0→chip1 (ICI RDMA)' if n >= 2 else 'self (1 chip)'}"),
        "value": round(nbytes / dt / 2**30, 3), "unit": "GiB/s",
        "vs_baseline": 1.0, "correct": ok, "n_devices": n, **rdma_extra,
    }


def matrix_decode_throughput(devices) -> dict:
    """Inference headline: greedy KV-cache decode tokens/s on one chip.

    Two decoders compiled at different ``max_new`` trip counts; the
    slope across them cancels BOTH the prefill pass and the dispatch
    round trip (the same two-point method as matmul_peak), leaving the
    steady-state per-token step cost of the cached decode loop."""
    import jax

    from ompi_tpu.models.decode import make_decoder
    from ompi_tpu.models.transformer import TransformerConfig
    from ompi_tpu.parallel.mesh import make_mesh

    on_tpu = devices[0].platform == "tpu"
    mesh = make_mesh({"dp": 1, "sp": 1, "tp": 1}, devices=devices[:1])
    if on_tpu:  # flagship dims (468M); generous KV room at batch 16
        cfg = TransformerConfig(
            vocab=32_000, d_model=2048, n_heads=16, n_layers=8,
            d_ff=8192, seq=512 + 256, attention="xla",
            compute_dtype="bfloat16")
        batch, prompt_len, lo, hi = 16, 512, 32, 192
    else:
        cfg = TransformerConfig(
            vocab=512, d_model=128, n_heads=8, n_layers=2, d_ff=256,
            seq=96, attention="xla", compute_dtype="float32")
        batch, prompt_len, lo, hi = 2, 32, 4, 16

    from ompi_tpu.models import transformer as tfm

    params = tfm.init_params(cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab,
                          size=(batch, prompt_len)).astype(np.int32)

    def timed(max_new: int) -> float:
        dec = make_decoder(cfg, mesh, max_new=max_new)
        out = dec(params, prompt)
        jax.block_until_ready(out)            # compile + warm
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out = dec(params, prompt)
            _ = int(np.asarray(out[0, -1]))   # value-readback fence
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo, t_hi = timed(lo), timed(hi)
    dt, extra = _slope_fields(t_lo, t_hi, lo, hi)
    row = {
        "metric": f"greedy KV-cache decode ({batch}x{prompt_len} prompt, "
                  f"1 chip)",
        "unit": "tokens/s", "vs_baseline": 1.0,
        "value": round(batch / dt, 1), **extra,
    }
    if "suspect" not in extra:
        row["ms_per_token"] = round(dt * 1e3, 3)
    return row


def matrix_flash_bwd_kernel(devices) -> dict:
    """Pallas flash-attention BACKWARD kernels (opt-in path): compile +
    run fwd+bwd with ops_flash_bwd_kernel=1 on the current backend.  On
    TPU this is the lowering smoke test for the (…, 8, block_q) lse/dm
    relayout (ADVICE r3 low): the kernels were previously exercised only
    in CPU interpret mode."""
    import jax
    import jax.numpy as jnp

    from ompi_tpu.core.config import var_registry
    from ompi_tpu.ops.flash_attention import flash_attention

    old = var_registry.get("ops_flash_bwd_kernel")
    var_registry.set("ops_flash_bwd_kernel", 1)
    try:
        b, t, h, d = 2, 512, 4, 128
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.normal(size=(b, t, h, d)),
                               jnp.bfloat16) for _ in range(3))

        def loss(q, k, v):
            return flash_attention(q, k, v, causal=True).astype(
                jnp.float32).sum()

        fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))  # bind ONCE:
        # a fresh jit wrapper per call would re-trace and the timed run
        # would measure compilation, not the kernels
        grads = fn(q, k, v)
        jax.block_until_ready(grads)
        t0 = time.perf_counter()
        grads = fn(q, k, v)
        jax.block_until_ready(grads)
        dt = time.perf_counter() - t0
        finite = all(bool(np.isfinite(np.asarray(
            g, dtype=np.float32)).all()) for g in grads)
        return {
            "metric": f"flash bwd pallas kernels (seq {t}, "
                      f"{devices[0].platform} lowering)",
            "value": round(dt * 1e3, 2), "unit": "ms", "vs_baseline": 1.0,
            "grads_finite": finite,
        }
    finally:
        var_registry.set("ops_flash_bwd_kernel", old)


def matrix_tuned_crossovers(devices, backend: str) -> dict:
    """Run the measured-crossover tuner (ompi_tpu.tools.tune) and — on a
    real backend — ship the generated rules file next to coll/xla, so the
    decision layer's thresholds become measured numbers with provenance
    instead of guesses (round-3 weak #5)."""
    from ompi_tpu.tools.tune import DEFAULT_OUT, tune_device_colls

    # ship only TPU-measured rules: writing CPU crossovers into the
    # package dir would silently change collective selection on every
    # later CPU run of this checkout (benchmarks must not mutate library
    # behavior as a side effect)
    out_path = DEFAULT_OUT if backend == "tpu" else None
    text, table = tune_device_colls(devices, out_path=out_path)
    rule_lines = [ln for ln in text.splitlines()
                  if ln and not ln.startswith("#")]
    return {
        "metric": f"measured coll crossovers ({len(devices)} dev)",
        "value": len(rule_lines), "unit": "rules", "vs_baseline": 1.0,
        "rules": rule_lines, "table_us": table,
        "shipped": out_path if out_path else "no (cpu fallback)",
    }


def run_matrix(devices, backend: str) -> list[dict]:
    rows: list[dict] = []
    # live view: a driver SIGTERM mid-matrix still emits the rows that
    # DID complete (the fallback path runs this before any recovery
    # probing, so a killed run carries the matrix, not just probe logs)
    _partial["matrix"] = rows
    for name, fn in (
            ("ring_latency", matrix_ring_latency),
            ("shm_pingpong", matrix_shm_pingpong),
            ("shm_msgrate", matrix_shm_msgrate),
            ("hbm_copy", lambda: matrix_hbm_copy(devices)),
            ("allreduce_sweep", lambda: matrix_allreduce_sweep(devices)),
            ("mesh_bcast_allgather",
             lambda: matrix_mesh_bcast_allgather(devices)),
            ("grad_reduce_scatter",
             lambda: matrix_grad_reduce_scatter(devices)),
            ("oshmem_device", lambda: matrix_oshmem_device(devices)),
            ("remote_dma", lambda: matrix_remote_dma(devices)),
            ("decode_throughput",
             lambda: matrix_decode_throughput(devices)),
            ("flash_bwd_kernel",
             lambda: matrix_flash_bwd_kernel(devices)),
            ("tuned_crossovers",
             lambda: matrix_tuned_crossovers(devices, backend))):
        t0 = time.perf_counter()
        try:
            row = fn()
        except Exception as e:  # noqa: BLE001 — every row must land
            row = {"metric": name, "value": 0, "unit": "error",
                   "vs_baseline": 0, "error": f"{type(e).__name__}: {e}"}
        row["config"] = name
        row["backend"] = backend
        row["wall_s"] = round(time.perf_counter() - t0, 2)
        _flag_suspect(row, backend)
        log(f"matrix[{name}]: {json.dumps(row)}")
        rows.append(row)
    try:
        with open(_MATRIX_PATH, "w") as f:
            json.dump(rows, f, indent=1)
        log(f"matrix written to {_MATRIX_PATH}")
    except OSError as e:
        log(f"matrix write failed: {e}")
    return rows


# ---------------------------------------------------------------------------


_FLAGSHIP_BUDGET_S = int(os.environ.get(
    "OMPI_TPU_BENCH_FLAGSHIP_BUDGET", "2100"))


def _flagship_guarded(kind: str) -> dict:
    """Run the flagship MFU in a SUBPROCESS with a wall budget: a
    stalled remote compile (the round-3 killer) then costs the headline
    row, not the whole bench — the final JSON line still prints, with
    the stall recorded.  --flagship-child is the child entry."""
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--flagship-child", kind],
            capture_output=True, text=True, timeout=_FLAGSHIP_BUDGET_S)
        for line in (proc.stdout or "").splitlines():
            if line.startswith("RESULT "):
                return json.loads(line[len("RESULT "):])
        return {"metric": "flagship transformer train-step MFU",
                "value": 0.0, "unit": "% MFU", "vs_baseline": 0.0,
                "error": f"flagship child rc={proc.returncode}",
                "stderr_tail": _tail(proc.stderr, 600)}
    except subprocess.TimeoutExpired as e:
        return {"metric": "flagship transformer train-step MFU",
                "value": 0.0, "unit": "% MFU", "vs_baseline": 0.0,
                "error": (f"flagship timed out after "
                          f"{_FLAGSHIP_BUDGET_S}s (compile stall)"),
                "stderr_tail": _tail(e.stderr, 600),
                "wall_s": round(time.perf_counter() - t0, 1)}


# partial evidence for the terminal-signal record: _probe_backend parks
# its attempts list here so a SIGTERM mid-recovery-window still emits
# a valid JSON record with the probes that DID run
_partial: dict = {}


def _arm_signal_record() -> None:
    """The one-JSON-line contract must survive the driver killing a
    too-long run (the 45-min recovery window is longer than round 4's
    wall): on SIGTERM, emit the record with the evidence so far.
    Disarm with _disarm_signal_record() right before the real record
    prints — the contract is ONE line, never two."""
    import signal

    def on_term(signum, frame):
        rec = {
            "metric": "bench run (interrupted before completion)",
            "value": 0.0, "unit": "% MFU", "vs_baseline": 0.0,
            "backend": "killed-mid-run",
            "error": f"interrupted by signal {signum}",
            "phase": _partial.get("phase", "probe/recovery"),
        }
        rec.update({k: v for k, v in _partial.items() if k != "phase"})
        # os.write, not print: a signal landing mid-print would make a
        # buffered-io call reentrant (RuntimeError inside the handler)
        os.write(1, (json.dumps(rec) + "\n").encode())
        os._exit(0)

    try:
        signal.signal(signal.SIGTERM, on_term)
    except ValueError:
        pass    # not the main thread (imported as a library)


def _disarm_signal_record() -> None:
    import signal

    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except ValueError:
        pass


def main() -> None:
    t_start = time.perf_counter()
    _enable_compile_cache()
    if len(sys.argv) >= 2 and sys.argv[1] == "--flagship-child":
        # child: no signal handler — a TERM'd child must die visibly so
        # the parent's rc check reports it, not exit 0 with a stray line
        kind = sys.argv[2] if len(sys.argv) > 2 else "cpu"
        if kind == "cpu":
            _force_cpu(8)
        rec = bench_flagship_mfu(kind)
        print("RESULT " + json.dumps(rec), flush=True)
        return
    _arm_signal_record()
    probe, attempts = _probe_backend()
    _partial["phase"] = "headline+matrix"   # initial probing is over
    if probe is None:
        _force_cpu(8)
        backend = "cpu-fallback"
        kind = "cpu"
    else:
        backend = probe["platform"]
        kind = probe.get("kind", backend)
        log(f"backend: {probe}")

    import jax

    devices = jax.devices()
    log(f"devices: {devices}")
    if probe is not None and len(devices) >= 2:
        result = bench_allreduce_busbw(devices)
    else:
        result = _flagship_guarded(kind)
    result["backend"] = backend
    if probe is None:
        # fallback evidence: every probe attempt's outcome + stderr tail
        result["probe_attempts"] = attempts
        # the round's TPU numbers exist even when the tunnel is dead at
        # bench time: the builder-run preflight artifact (same
        # methodology, committed in-repo)
        pf = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_TPU_PREFLIGHT_r04.json")
        if os.path.exists(pf):
            result["tpu_evidence"] = (
                "BENCH_TPU_PREFLIGHT_r04.json — builder-run on the live "
                "chip (flagship headline + matrix + sweep_update with "
                "the measured-best MFU)")
    elif len(attempts) > 1:
        result["probe_attempts"] = [
            {k: a[k] for k in ("attempt", "outcome") if k in a}
            for a in attempts]
    try:
        rows = run_matrix(devices, backend)
    except Exception as e:  # noqa: BLE001 — matrix must not kill the primary
        log(f"matrix failed: {type(e).__name__}: {e}")
        rows = _partial.get("matrix", [])
    if probe is None:
        # outage mode: the matrix rows ride INSIDE the one-line record
        # (BENCH_MATRIX.json may never be collected from a killed box),
        # and only now — evidence banked — may recovery probes spend
        # what remains of the driver's budget
        result["matrix"] = rows
        _partial["phase"] = "recovery-window"
        late = _probe_recovery(
            attempts, _recovery_window_s(time.perf_counter() - t_start))
        if late is not None:
            result["late_backend"] = late
            result["note"] = (
                "backend revived AFTER the CPU evidence was banked; "
                "numbers above are cpu-fallback — rerun for TPU rows")
    result["wall_s"] = round(time.perf_counter() - t_start, 1)
    # provenance: the transport-stack counter snapshot (pack-plan
    # classes, zero-copy vs packed sends, shm ring traffic) rides in the
    # record, so a BENCH_*.json row carries which fast paths its own run
    # actually exercised
    result["counters"] = _counters_snapshot()
    _partial["counters"] = result["counters"]
    # the real record is about to print — a TERM from here on must not
    # add a second JSON line (default action: die without output; the
    # microsecond race loses the record, duplicates never happen)
    _disarm_signal_record()
    print(json.dumps(result), flush=True)


def _counters_snapshot() -> dict:
    """The flight-recorder counter block (never raises — the one-line
    record contract survives an import problem)."""
    try:
        from ompi_tpu.mpi import trace as _trace

        return _trace.counters_snapshot()
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — stdout must stay one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "bench error", "value": 0, "unit": "error",
            "vs_baseline": 0, "error": f"{type(e).__name__}: {e}"}),
            flush=True)
        raise SystemExit(0)
