"""Ring message pass — behavioral equivalent of the reference's
examples/ring_c.c:1-79 (BASELINE.json config 1): rank 0 injects a counter,
each rank forwards around the ring decrementing at rank 0 until it reaches 0.

Run:  tpurun -np 4 -- python examples/ring.py
"""

import numpy as np

import ompi_tpu


def main() -> None:
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size
    next_rank = (rank + 1) % size
    prev_rank = (rank - 1) % size

    if rank == 0:
        message = np.array([10], dtype=np.int32)
        print(f"Process 0 sending {int(message[0])} to {next_rank}, "
              f"tag 201 ({size} processes in ring)")
        comm.send(message, dest=next_rank, tag=201)
        print("Process 0 sent to", next_rank)

    while True:
        message = comm.recv(source=prev_rank, tag=201)
        if rank == 0:
            message = message - 1
            print(f"Process 0 decremented value: {int(message[0])}")
        if int(message[0]) == 0 and rank != 0:
            print(f"Process {rank} exiting")
            comm.send(message, dest=next_rank, tag=201)
            break
        comm.send(message, dest=next_rank, tag=201)
        if rank == 0 and int(message[0]) == 0:
            print(f"Process {rank} exiting")
            # absorb the final message so no rank blocks forever
            comm.recv(source=prev_rank, tag=201)
            break

    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
