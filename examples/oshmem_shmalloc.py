"""Symmetric-heap allocation (≈ examples/oshmem_shmalloc.c): every PE
allocates the same-shaped block from the symmetric heap, fills it, and
frees it — the collective-allocation contract shmalloc/shfree promise.

Run:  tpurun -np 4 -- python examples/oshmem_shmalloc.py
"""

import numpy as np

from ompi_tpu import shmem


def main() -> None:
    shmem.init()
    me = shmem.my_pe()
    # shmem.array is the shmalloc analog: symmetric (same shape/dtype on
    # every PE, collectively allocated, same heap index everywhere)
    block = shmem.array((256,), dtype=np.float64)
    block[:] = float(me)
    shmem.barrier_all()
    assert (np.asarray(block[:]) == float(me)).all()
    shmem.free(block)
    print(f"PE {me}: shmalloc/shfree ok")
    shmem.finalize()


if __name__ == "__main__":
    main()
