"""shrink_allreduce — the ULFM shrink-and-continue recipe, end to end.

An iterative allreduce loop that loses a rank mid-run (an injected kill
from a fault plan, or nothing when run without one), detects the failure,
revokes the communicator so every survivor unblocks, agrees on the
failed set, shrinks to the survivor communicator, restores from the last
*agreed* checkpoint snapshot, and finishes with the correct sum.

Run it under the notify errmgr policy so the runtime propagates the
death instead of killing the job:

    tpurun -np 4 --mca errmgr notify \
        --mca faultinject_plan "rank=2:kill@step=3" \
        python examples/shrink_allreduce.py

Protocol per step (the canonical ULFM loop):

1. every rank contributes ``id*10 + step`` (id = its ORIGINAL world
   rank, stable across shrinks) to an allreduce;
2. a rank whose allreduce raised PROC_FAILED/REVOKED calls
   ``comm.revoke()`` IMMEDIATELY — this is the load-bearing ULFM move:
   a peer still blocked inside the collective is waiting on a *survivor*
   that already errored out, and only the revocation's poison unblocks
   it (the failure alone never would);
3. every rank votes ``comm.agree(step_succeeded)`` — a step only counts
   when EVERY member completed it, so survivors can never commit a sum
   the failure made inconsistent;
4. agreed → checkpoint (step, acc) and advance;  not agreed →
   ``shrink()`` to the survivors, restore the last agreed snapshot, and
   repeat the step on the smaller world.

The final acc on every survivor equals: full-world sums for the steps
agreed before the kill, survivor-only sums after — tools/chaos_soak.py
recomputes that expectation and asserts it.
"""

import os
import sys
import time

import numpy as np

import ompi_tpu
from ompi_tpu.ckpt.store import SnapshotStore
from ompi_tpu.mpi.constants import (
    ERR_PROC_FAILED, ERR_REVOKED, MPIException,
)
from ompi_tpu.testing import faultinject


def main() -> int:
    comm = ompi_tpu.init()
    my_id = comm.rank        # stable identity; comm.rank changes on shrink
    steps = int(os.environ.get("SHRINK_DEMO_STEPS", "6"))
    ckpt_dir = os.environ.get("CKPT_DIR")
    store = (SnapshotStore(ckpt_dir, job=f"rank{my_id}")
             if ckpt_dir else None)

    acc, step, shrinks = 0.0, 0, 0
    while step < steps:
        faultinject.step()   # a plan's kill@step fires here (or no-op)
        ok = True
        t_op = time.monotonic()
        try:
            got = comm.allreduce(np.array([float(my_id * 10 + step)]))
            result = float(got[0])
        except MPIException as e:
            if e.error_class not in (ERR_PROC_FAILED, ERR_REVOKED):
                raise
            ok, result = False, 0.0
            # time-to-error: how long the collective blocked before the
            # failure surfaced (chaos_soak asserts this stays in the
            # detector window, nowhere near the 60 s coll_shm_timeout)
            print(f"id {my_id} detect_dt={time.monotonic() - t_op:.2f}",
                  flush=True)
            # revoke BEFORE agreeing: survivors still blocked in the
            # collective are waiting on ranks that already errored out —
            # the revocation is what unblocks them into the agree below
            comm.revoke()
        try:
            agreed = comm.agree(ok)
        except MPIException as e:
            if e.error_class != ERR_PROC_FAILED:
                raise
            agreed = False
        if agreed:
            acc += result
            if store is not None:
                store.write_rank(step, 0, {"step": np.int64(step),
                                           "acc": np.float64(acc)})
                store.commit(step, 1)
            step += 1
            continue
        # somebody failed this step: drop the dead, rewind to the last
        # agreed snapshot, redo the step on the survivor communicator
        comm.revoke()   # idempotent; covers an agree()==False-only path
        old_members = set(comm.group.ranks)
        comm = comm.shrink()
        lost = sorted(old_members - set(comm.group.ranks))
        shrinks += 1
        if store is not None and store.latest() is not None:
            seq = store.latest()
            state = store.load_rank(seq, 0)
            step, acc = int(state["step"]) + 1, float(state["acc"])
        else:
            step, acc = 0, 0.0
        print(f"id {my_id}: shrank to size {comm.size} (lost {lost}); "
              f"resuming at step {step}", flush=True)

    print(f"id {my_id} final acc={acc:.0f} size={comm.size} "
          f"shrinks={shrinks}", flush=True)
    ompi_tpu.finalize()
    return 0


if __name__ == "__main__":
    sys.exit(main())
