"""Matched probe: a multi-threaded task-pull server.

    tpurun -np 4 python examples/mprobe_task_queue.py

Rank 0 runs TWO worker threads pulling tasks from any source with
``mprobe`` — the MPI-3 matched probe is the only thread-safe way to
probe-then-receive with wildcards: the probe atomically detaches the
message, so the sibling thread can never steal it between the probe and
the receive (a plain probe+recv pair races exactly there).
"""

import threading

import numpy as np

import ompi_tpu

TASKS_PER_RANK = 8


def main() -> None:
    comm = ompi_tpu.init()
    if comm.size < 2:
        raise SystemExit("need at least 2 ranks")
    if comm.rank == 0:
        target = (comm.size - 1) * TASKS_PER_RANK
        got: list = []
        lock = threading.Lock()

        def worker(wid: int) -> None:
            while True:
                with lock:
                    if len(got) >= target:
                        return
                try:
                    msg, st = comm.mprobe(source=-1, tag=7, timeout=0.2)
                except TimeoutError:
                    continue                  # re-check the done counter
                task = comm.mrecv(message=msg)
                with lock:
                    got.append((wid, st.source, int(task[0])))

        ts = [threading.Thread(target=worker, args=(w,)) for w in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        per_worker = {w: sum(1 for x in got if x[0] == w) for w in (0, 1)}
        tasks = sorted(x[2] for x in got)
        expect = sorted(r * 100 + i for r in range(1, comm.size)
                        for i in range(TASKS_PER_RANK))
        assert tasks == expect, "every task delivered exactly once"
        print(f"rank 0 processed {len(got)} tasks across workers "
              f"{per_worker} — no duplicates, no losses")
    else:
        for i in range(TASKS_PER_RANK):
            comm.send(np.array([comm.rank * 100 + i], np.int64),
                      dest=0, tag=7)
    comm.barrier()
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
