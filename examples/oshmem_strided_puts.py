"""Strided puts (≈ examples/oshmem_strided_puts.c): PE 0 writes every other
element of PE 1's symmetric array with shmem_iput semantics.

Run:  tpurun -np 2 -- python examples/oshmem_strided_puts.py
"""

import numpy as np

from ompi_tpu import shmem


def main() -> None:
    shmem.init()
    me = shmem.my_pe()
    assert shmem.n_pes() >= 2, "needs at least 2 PEs"
    dest = shmem.array((10,), dtype=np.int64)
    if me == 0:
        dest.iput(1, np.array([1, 2, 3, 4, 5]), target_stride=2)
    dest.barrier()
    if me == 1:
        got = dest[:].tolist()
        assert got[::2] == [1, 2, 3, 4, 5], got
        print(f"PE 1: strided put ok: {got}")
    shmem.finalize()


if __name__ == "__main__":
    main()
