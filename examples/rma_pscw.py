"""RMA demo: fence, PSCW epochs, get_accumulate, request ops, dynamic windows.

Run:  tpurun -np 4 python examples/rma_pscw.py
(≈ the reference's one-sided usage in test suites; MPI-3.1 ch. 11 semantics)
"""

import numpy as np

import ompi_tpu

ompi_tpu.init()
comm = ompi_tpu.COMM_WORLD
rank, size = comm.rank, comm.size

# -- fence + get_accumulate: a shared atomic counter ------------------------
win = ompi_tpu.Window(comm, size=1, dtype=np.int64)
win.fence()
ticket = int(win.get_accumulate(0, np.array([1]), ompi_tpu.SUM)[0])
win.fence()
total = int(win.get(0, count=1)[0])
assert total == size, (total, size)
print(f"rank {rank}: ticket={ticket} total={total}")
win.free()

# -- PSCW: even ranks expose, odd ranks access ------------------------------
win = ompi_tpu.Window(comm, size=size, dtype=np.int64)
evens = list(range(0, size, 2))
odds = list(range(1, size, 2))
if rank % 2 == 0:
    win.post(odds)
    win.wait()
    got = win.buf[: len(odds)].tolist()
    assert got == [o + 1 for o in odds], got
    print(f"rank {rank}: PSCW exposure saw {got}")
else:
    win.start(evens)
    for t in evens:
        win.rput(t, np.array([rank + 1]), offset=rank // 2).wait()
    win.complete()
comm.barrier()
win.free()

# -- dynamic window ---------------------------------------------------------
win = ompi_tpu.Window.create_dynamic(comm, dtype=np.float64)
region = np.zeros(4)
base = win.attach(region)
bases = [int(np.asarray(b)[0]) for b in comm.allgather(np.array([base]))]
win.fence()
right = (rank + 1) % size
win.put(right, np.full(4, float(rank)), offset=bases[right])
win.fence()
assert region.tolist() == [float((rank - 1) % size)] * 4, region
win.detach(base)
win.free()
print(f"rank {rank}: dynamic window ok")

ompi_tpu.finalize()
