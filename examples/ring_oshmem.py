"""Ring message pass over the symmetric heap with wait_until
(≈ examples/ring_oshmem_c.c): a counter circulates the PE ring; PE 0
decrements it each lap; each PE exits after its final put (PE 0's
closing 0-put lands in an already-exited neighbor's slot, completed by
finalize's collective teardown — the reference behaves the same way).

Run:  tpurun -np 4 -- python examples/ring_oshmem.py
"""

import numpy as np

from ompi_tpu import shmem


def main() -> None:
    shmem.init()
    me, n = shmem.my_pe(), shmem.n_pes()
    rbuf = shmem.array((1,), dtype=np.int64)
    rbuf[:] = -1
    shmem.barrier_all()  # everyone's rbuf exists before the first put
    nxt = (me + 1) % n
    message = 10
    if me == 0:
        print(f"PE 0 puts message {message} to {nxt} ({n} PEs in ring)")
        rbuf.put(nxt, np.array([message]))
    while message > 0:
        rbuf.wait_until("eq", message)
        if me == 0:
            message -= 1
            print(f"PE 0 decremented value: {message}")
        rbuf.put(nxt, np.array([message]))
        if me != 0:
            message -= 1
    shmem.finalize()
    print(f"PE {me} exiting")


if __name__ == "__main__":
    main()
