"""On-node collective round-trip: allreduce + bcast + barrier + allgather
through whatever coll component owns the slots, printing a verifiable
answer per rank plus the coll/shm arena pvars — the CI coll-smoke
driver (run under tpurun with 4 ranks; pass --mca coll_shm_enable 0 to
exercise the coll/host fallback, the pvars then read 0/0).

    tpurun -np 4 python examples/shm_coll_demo.py
"""

from __future__ import annotations

import numpy as np

import ompi_tpu


def main() -> None:
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size

    comm.barrier()
    total = comm.allreduce(np.arange(8.0) + rank)
    want_total = np.arange(8.0) * size + sum(range(size))
    assert np.array_equal(total, want_total), (total, want_total)

    seen = comm.bcast(np.array([3.0, 1.0, 4.0, 1.0, 5.0])
                      if rank == 0 else None, root=0)
    assert np.array_equal(seen, [3.0, 1.0, 4.0, 1.0, 5.0]), seen

    gathered = comm.allgather(np.array([rank * rank]))
    assert np.array_equal(gathered.reshape(-1),
                          [r * r for r in range(size)]), gathered

    # one large allreduce so the segmented pipeline runs too
    big = comm.allreduce(np.ones(200_000) * (rank + 1))
    assert float(big[0]) == sum(range(1, size + 1)), big[0]
    comm.barrier()

    from ompi_tpu.mpi import trace

    fanin = trace.counters["coll_shm_fanin_total"]
    fanout = trace.counters["coll_shm_fanout_total"]
    fallback = trace.counters["coll_shm_fallback_total"]
    provider = comm.coll.providers.get("allreduce", "?")
    print(f"rank {rank}: coll ok sum={float(total.sum()):.0f} "
          f"provider={provider} fanin={fanin} fanout={fanout} "
          f"fallback={fallback}", flush=True)

    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
