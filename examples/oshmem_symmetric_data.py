"""Symmetric data access (≈ examples/oshmem_symmetric_data.c): PE 0 gets
every other PE's symmetric array contents and verifies them.

Run:  tpurun -np 4 -- python examples/oshmem_symmetric_data.py
"""

import numpy as np

from ompi_tpu import shmem

N = 6


def main() -> None:
    shmem.init()
    me, n = shmem.my_pe(), shmem.n_pes()
    data = shmem.array((N,), dtype=np.int64)
    data[:] = me * 100 + np.arange(N)
    shmem.barrier_all()
    if me == 0:
        for pe in range(n):
            got = data.get(pe)
            want = pe * 100 + np.arange(N)
            assert (got == want).all(), (pe, got)
        print(f"PE 0: verified symmetric data on all {n} PEs")
    shmem.barrier_all()
    shmem.finalize()


if __name__ == "__main__":
    main()
