"""The classic ring example written against the mpi4py API — runs here
unchanged except for the import line (was: ``from mpi4py import MPI``).

≈ /root/reference/examples/ring_c.c:1-79, via the compat facade.

    tpurun -np 4 python examples/mpi4py_ring.py
"""

import numpy as np

from ompi_tpu.compat import MPI

comm = MPI.COMM_WORLD
rank = comm.Get_rank()
size = comm.Get_size()
next_rank = (rank + 1) % size
prev_rank = (rank - 1) % size

msg = np.array([10], dtype=np.int32)
if rank == 0:
    print(f"Process 0 sending {msg[0]} to {next_rank}, "
          f"tag 201 ({size} processes in ring)")
    comm.Send([msg, MPI.INT], dest=next_rank, tag=201)

while True:
    comm.Recv([msg, MPI.INT], source=prev_rank, tag=201)
    if rank == 0:
        msg[0] -= 1
        print(f"Process 0 decremented value: {msg[0]}")
    comm.Send([msg, MPI.INT], dest=next_rank, tag=201)
    if msg[0] == 0:
        print(f"Process {rank} exiting")
        break

# rank 0 drains the final message still circling the ring
if rank == 0:
    comm.Recv([msg, MPI.INT], source=prev_rank, tag=201)

MPI.Finalize()
