"""KV-cache greedy generation on a device mesh (models/decode.py).

One compiled program: prefill through the training backbone, then a
lax.scan of cached single-token steps — batch sharded over dp, heads
(and the KV cache) over tp.

Run:  python examples/generate.py          # uses all local devices
"""

import numpy as np


def main() -> None:
    import jax

    from ompi_tpu.models import transformer as tfm
    from ompi_tpu.models.decode import make_decoder
    from ompi_tpu.parallel.mesh import make_mesh, mesh_shape_for

    n = len(jax.devices())
    shape = mesh_shape_for(n, ["dp", "tp"])
    mesh = make_mesh({"dp": shape["dp"], "sp": 1, "tp": shape["tp"]},
                     devices=jax.devices())
    cfg = tfm.TransformerConfig(
        vocab=512, d_model=128, n_heads=8, n_layers=2, d_ff=512,
        seq=64, attention="xla", compute_dtype="float32")
    params = tfm.init_params(cfg)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab,
                          size=(2 * shape["dp"], 8)).astype(np.int32)
    dec = make_decoder(cfg, mesh, max_new=12)
    out = np.asarray(dec(params, prompt))
    print(f"mesh {dict(mesh.shape)}; prompt {prompt.shape} -> {out.shape}")
    for row in out[:2]:
        print("  ", row.tolist())


if __name__ == "__main__":
    main()
