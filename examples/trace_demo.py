"""Flight-recorder demo: exercise every traced transport layer.

Touches p2p (eager AND rendezvous), a collective, a derived datatype
pack, MPI-IO, and an RMA window, so a traced run produces spans in all
five acceptance categories (pml, btl, coll, datatype, io) plus osc.

Run:  tpurun -np 2 --trace -- python examples/trace_demo.py
Then: python tools/trace_export.py --dir "$TMPDIR" -o trace.json
and load trace.json in chrome://tracing or ui.perfetto.dev.
"""

import os
import tempfile

import numpy as np

import ompi_tpu
from ompi_tpu.mpi import datatype as dt
from ompi_tpu.mpi import io as mpiio
from ompi_tpu.mpi import osc


def main() -> None:
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size
    peer = (rank + 1) % size

    # p2p: one eager message and one past the eager limit (rendezvous)
    rreq = comm.irecv(source=(rank - 1) % size, tag=1)
    comm.send(np.arange(64, dtype=np.float64), dest=peer, tag=1)
    rreq.wait()
    big = np.ones(128 * 1024, dtype=np.float32)     # 512 KiB > eager limit
    rreq = comm.irecv(np.empty_like(big), source=(rank - 1) % size, tag=2)
    comm.send(big, dest=peer, tag=2)
    rreq.wait()

    # coll: an allreduce plus the barrier's dissemination traffic
    total = comm.allreduce(np.full(8, rank, dtype=np.int64))
    comm.barrier()

    # datatype: a strided vector type, committed + packed on the wire
    vec = dt.INT32.vector(count=16, blocklength=2, stride=4).commit()
    buf = np.arange(64, dtype=np.int32)
    rreq = comm.irecv(np.empty(32, np.int32), source=(rank - 1) % size,
                      tag=3, datatype=dt.INT32, count=32)
    comm.send(buf, dest=peer, tag=3, datatype=vec, count=1)
    rreq.wait()

    # io: per-rank write + read-back through a shared file
    path = os.path.join(tempfile.gettempdir(),
                        f"otpu_trace_demo_{os.environ.get('OMPI_TPU_JOBID', 0)}.bin")
    fh = mpiio.File(comm, path,
                    mpiio.MODE_RDWR | mpiio.MODE_CREATE)
    fh.set_view(etype=dt.FLOAT64)
    fh.write_at(rank * 16, np.full(16, float(rank), dtype=np.float64))
    back = fh.read_at(rank * 16, 16)
    fh.close()
    if rank == 0:
        try:
            os.unlink(path)
        except OSError:
            pass

    # osc: a fence epoch with a put
    win = osc.Window(comm, buffer=np.zeros(8, dtype=np.float64))
    win.fence()
    win.put(peer, np.full(8, float(rank + 1)))
    win.fence()
    win.free()

    print(f"rank {rank}: allreduce={int(total[0])}, "
          f"io_back={back[:2]}, demo done")
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
