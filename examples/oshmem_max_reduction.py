"""Max reduction across PEs (≈ examples/oshmem_max_reduction.c):
every PE fills a symmetric array with rank-dependent values; max_to_all
leaves the elementwise maximum on every PE.

Run:  tpurun -np 4 -- python examples/oshmem_max_reduction.py
"""

import numpy as np

from ompi_tpu import shmem
from ompi_tpu.mpi import op as op_mod

N = 8


def main() -> None:
    shmem.init()
    me, n = shmem.my_pe(), shmem.n_pes()
    src = shmem.array((N,), dtype=np.int64)
    src[:] = me + np.arange(N)
    shmem.barrier_all()
    shmem.to_all(src, op=op_mod.MAX)
    expected = (n - 1) + np.arange(N)
    assert (src[:] == expected).all(), (src[:], expected)
    print(f"PE {me}: max reduction ok: {src[:].tolist()}")
    shmem.finalize()


if __name__ == "__main__":
    main()
