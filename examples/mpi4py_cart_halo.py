"""2-D halo exchange through the mpi4py facade's Cartesian topology —
the canonical stencil-code skeleton, unchanged from how it reads under
mpi4py (only the import differs).

Run:  tpurun -np 4 -- python examples/mpi4py_cart_halo.py
"""

import numpy as np

from ompi_tpu.compat import MPI


def main() -> None:
    comm = MPI.COMM_WORLD
    dims = MPI.Compute_dims(comm.Get_size(), 2)
    cart = comm.Create_cart(dims, periods=[True, True])
    coords = cart.coords

    # local tile with a 1-cell halo; interior filled with my rank
    n = 4
    tile = np.full((n + 2, n + 2), -1.0)
    tile[1:-1, 1:-1] = float(cart.Get_rank())

    for direction in (0, 1):
        src, dst = cart.Shift(direction, 1)
        recv_lo, recv_hi = np.zeros(n), np.zeros(n)
        if direction == 0:
            send_lo, send_hi = tile[1, 1:-1].copy(), tile[-2, 1:-1].copy()
        else:
            send_lo, send_hi = tile[1:-1, 1].copy(), tile[1:-1, -2].copy()
        # exchange both faces (periodic: neighbors always exist)
        cart.Sendrecv(send_hi, dst, 0, recv_lo, src, 0)
        cart.Sendrecv(send_lo, src, 1, recv_hi, dst, 1)
        if direction == 0:
            tile[0, 1:-1], tile[-1, 1:-1] = recv_lo, recv_hi
        else:
            tile[1:-1, 0], tile[1:-1, -1] = recv_lo, recv_hi

    lo0, _ = cart.Shift(0, 1)
    assert tile[0, 1] == float(lo0), (tile[0, 1], lo0)
    print(f"rank {cart.Get_rank()} coords {coords}: halo exchange ok "
          f"(north face from rank {int(tile[0, 1])})")
    MPI.Finalize()


if __name__ == "__main__":
    main()
