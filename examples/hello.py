"""Hello world (≈ examples/hello_c.c): rank/size + identity print.

Run:  tpurun -np 4 -- python examples/hello.py
"""

import ompi_tpu


def main() -> None:
    comm = ompi_tpu.init()
    print(f"Hello, world, I am {comm.rank} of {comm.size}")
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
