"""End-to-end training on a device mesh: the full stack in one file.

data pipeline (deterministic windows, dp-sharded double-buffered
prefetch) → 3D-parallel transformer (dp × sp × tp shard_map) → in-jit
chained train steps → snapshot checkpoint → resume reproducing the
exact batch stream from the saved step.

Run:  python examples/train.py [--steps 6] [--ckpt-dir /tmp/train_ckpt]
"""

import argparse
import tempfile

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--ckpt-dir", default=None)
    args = ap.parse_args()

    import jax

    from ompi_tpu.ckpt.store import SnapshotStore
    from ompi_tpu.models import data as data_mod
    from ompi_tpu.models import transformer as tfm
    from ompi_tpu.parallel.mesh import make_mesh, mesh_shape_for

    n = len(jax.devices())
    shape = mesh_shape_for(n, ["dp", "tp"])
    mesh = make_mesh({"dp": shape["dp"], "sp": 1, "tp": shape["tp"]},
                     devices=jax.devices())
    cfg = tfm.TransformerConfig(
        vocab=512, d_model=128, n_heads=8, n_layers=2, d_ff=512,
        seq=64, attention="xla", compute_dtype="float32",
        adam_mu_dtype="bfloat16")
    batch = 4 * shape["dp"]

    params = tfm.init_params(cfg)
    step, init_opt = tfm.make_train_step(cfg, mesh, lr=3e-3)
    opt_state = init_opt(params)

    corpus = (np.arange(32_768) * 2654435761 % cfg.vocab).astype(np.int32)
    src = data_mod.ArraySource(corpus, seed=0)
    stream = data_mod.train_stream(src, mesh, batch, cfg.seq)

    store = SnapshotStore(args.ckpt_dir or tempfile.mkdtemp(), job="demo")
    half = args.steps // 2
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, next(stream))
        print(f"step {i}: loss {float(loss):.4f}")
        if i + 1 == half:
            store.write_rank(0, 0, {"w1": params["w1"],
                                    "step": np.int64(i + 1)})
            store.commit(0, nranks=1)
            print(f"checkpoint at step {i + 1} -> {store.snapshot_dir(0)}")

    # resume: the (seed, step) contract reproduces the stream exactly
    resumed = data_mod.train_stream(src, mesh, batch, cfg.seq,
                                    start_step=half)
    live = data_mod.train_stream(src, mesh, batch, cfg.seq)
    for _ in range(half + 1):     # batches 0..half; keep batch[half]
        ref = next(live)
    np.testing.assert_array_equal(np.asarray(next(resumed)),
                                  np.asarray(ref))
    print("resume: batch stream reproduced from checkpointed step — ok")


if __name__ == "__main__":
    main()
