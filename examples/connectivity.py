"""All-pairs connectivity check (≈ examples/connectivity_c.c): every ordered
pair exchanges a token; verbose mode prints each edge.

Run:  tpurun -np 4 -- python examples/connectivity.py [-v]
"""

import sys

import numpy as np

import ompi_tpu


def main() -> None:
    verbose = "-v" in sys.argv
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size
    for i in range(size):
        for j in range(i + 1, size):
            if rank == i:
                token = np.array([j], dtype=np.int32)
                comm.send(token, dest=j, tag=i)
                back = comm.recv(source=j, tag=j)
                assert int(back[0]) == i
                if verbose:
                    print(f"Checking connection between ranks {i} and {j}")
            elif rank == j:
                tok = comm.recv(source=i, tag=i)
                assert int(tok[0]) == j
                comm.send(np.array([i], dtype=np.int32), dest=i, tag=j)
    comm.barrier()
    if rank == 0:
        print(f"Connectivity test on {size} processes PASSED.")
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
