"""Circular shift through the symmetric heap (≈ examples/oshmem_circular_shift.c):
each PE puts its value into the next PE's symmetric slot; after the barrier
every PE holds its left neighbor's value.

Run:  tpurun -np 4 -- python examples/oshmem_circular_shift.py
"""

import numpy as np

from ompi_tpu import shmem


def main() -> None:
    shmem.init()
    me, n = shmem.my_pe(), shmem.n_pes()
    dest = shmem.array((1,), dtype=np.int64)
    next_pe = (me + 1) % n
    dest.put(next_pe, np.array([me + 10]))
    dest.barrier()  # completes all puts everywhere
    want = ((me - 1) % n) + 10
    assert int(dest[0]) == want, (int(dest[0]), want)
    print(f"PE {me}: circular shift ok (got {int(dest[0])})")
    shmem.finalize()


if __name__ == "__main__":
    main()
