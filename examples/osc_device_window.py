"""One-sided device RMA: a DeviceWindow over the chip mesh.

Run on any machine (falls back to a virtual 8-device CPU mesh when no
multi-chip TPU is present):

    python examples/osc_device_window.py

The put is NOT a collective: bytes cross the interconnect exactly once,
origin→target, through a pallas remote-DMA kernel — the osc/rdma
strategy on ICI.
"""

import numpy as np


def main() -> None:
    import os

    import jax

    # default to the virtual CPU mesh: probing an accelerator backend can
    # block when its tunnel is down; opt into real chips explicitly
    if os.environ.get("OMPI_TPU_EXAMPLE_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)

    from ompi_tpu.mpi.device_comm import device_world
    from ompi_tpu.mpi.osc import DeviceWindow
    from ompi_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(devices=jax.devices())
    comm = device_world(mesh)
    n = comm.size
    if n < 2:
        raise SystemExit("need >= 2 devices (origin and target differ); "
                         "unset OMPI_TPU_EXAMPLE_TPU for the CPU mesh")
    print(f"{n}-device window over {jax.default_backend()}")

    win = DeviceWindow(comm, local_shape=(4, 128), dtype=np.float32)
    win.put(np.full((4, 128), 42.0, np.float32), origin=0, target=n - 1)
    win.fence()
    assert np.all(win.local(n - 1) == 42.0)
    assert np.all(win.local(0) == 0.0)
    fetched = win.get(origin=1, target=n - 1)
    assert np.all(fetched == 42.0)
    print(f"one-sided put landed on device {n - 1}; "
          f"one-sided get fetched it back: {fetched[0, 0]}")
    win.free()


if __name__ == "__main__":
    main()
