"""Persistent & partitioned round-trip: bind ONE allreduce plan, Start
it N times, verify every iteration, and print the bind/start/fallback
pvar accounting the CI coll-smoke driver asserts (binds=1 starts=N
fallback=0 per rank proves the decision/slot/hierarchy work was paid
exactly once); then a pairwise-ring partitioned psend/precv exchange
with out-of-order Pready.

    tpurun -np 4 python examples/persistent_coll_demo.py
"""

from __future__ import annotations

import numpy as np

import ompi_tpu


def main() -> None:
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size
    from ompi_tpu.mpi import trace
    from ompi_tpu.mpi.request import start_all

    comm.barrier()
    b0 = trace.counters["coll_persistent_binds_total"]
    s0 = trace.counters["coll_persistent_starts_total"]
    f0 = trace.counters["coll_shm_fallback_total"]

    N = 16
    x = np.zeros(64)
    req = comm.allreduce_init(x)
    total = None
    for k in range(N):
        x[...] = np.arange(64.0) + rank + k
        req.start()
        total = req.wait()
        want = np.arange(64.0) * size + sum(range(size)) + size * k
        assert np.array_equal(total, want), (k, total, want)

    binds = trace.counters["coll_persistent_binds_total"] - b0
    starts = trace.counters["coll_persistent_starts_total"] - s0
    fallback = trace.counters["coll_shm_fallback_total"] - f0
    print(f"rank {rank}: persistent ok sum={float(total.sum()):.0f} "
          f"provider={req.provider} binds={binds} starts={starts} "
          f"fallback={fallback}", flush=True)

    # partitioned pairwise ring: send to the right, receive from the
    # left, partitions readied out of order
    sbuf = np.arange(32.0) + rank
    rbuf = np.zeros(32)
    ps = comm.psend_init(sbuf, dest=(rank + 1) % size, tag=1,
                         partitions=4)
    pr = comm.precv_init(rbuf, source=(rank - 1) % size, tag=1,
                         partitions=4)
    start_all([ps, pr])
    for i in (2, 0, 3, 1):
        ps.pready(i)
    ps.wait()
    pr.wait()
    assert np.array_equal(rbuf, np.arange(32.0) + (rank - 1) % size), rbuf
    print(f"rank {rank}: partitioned ok", flush=True)

    comm.barrier()
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
