"""Collective MPI-IO of a block-distributed matrix (mpi4py spelling).

Each rank owns one block of an N×N float64 matrix on a √P×√P process
grid; a darray file view lets every rank write its block to the ONE
shared file with a single collective call (the fcoll aggregators turn
the interleaved row segments into large contiguous file writes), then
read it back through the same view.

Run:  tpurun -np 4 python examples/mpiio_darray.py
"""

import math
import os
import tempfile

import numpy as np

from ompi_tpu.compat import MPI

comm = MPI.COMM_WORLD
rank, size = comm.Get_rank(), comm.Get_size()
q = int(math.isqrt(size))
assert q * q == size, "run with a square process count (1, 4, 9, ...)"

N = 8 * q                      # global matrix side; 8x8 block per rank
# unique per-run file (a fixed name would collide across or between
# runs — MODE_CREATE doesn't truncate); rank 0 names it, all agree
if rank == 0:
    fd, path = tempfile.mkstemp(suffix=".darray.bin")
    os.close(fd)
else:
    path = None
path = comm.bcast(path, root=0)

try:
    view = MPI.DOUBLE.Create_darray(
        size, rank, [N, N],
        [MPI.DISTRIBUTE_BLOCK, MPI.DISTRIBUTE_BLOCK],
        [MPI.DISTRIBUTE_DFLT_DARG, MPI.DISTRIBUTE_DFLT_DARG],
        [q, q]).Commit()

    # my block, filled with rank-stamped values
    block = np.full((N // q) * (N // q), float(rank), np.float64)
    block += np.arange(block.size) / 1000.0

    f = MPI.File.Open(comm, path, MPI.MODE_RDWR | MPI.MODE_CREATE)
    f.Set_view(disp=0, etype=MPI.DOUBLE, filetype=view)
    f.Write_at_all(0, block)

    back = np.zeros_like(block)
    f.Read_at_all(0, back)
    f.Close()
    assert np.array_equal(back, block), "roundtrip mismatch"

    # rank 0 checks the assembled global matrix on disk
    comm.Barrier()
    if rank == 0:
        disk = np.fromfile(path, np.float64).reshape(N, N)
        b = N // q
        for r in range(size):
            pr, pc = divmod(r, q)
            got = disk[pr * b:(pr + 1) * b, pc * b:(pc + 1) * b]
            assert abs(got[0, 0] - float(r)) < 1e-9, (r, got[0, 0])
        print(f"darray collective IO ok: {N}x{N} matrix, {size} ranks, "
              f"one shared file")
finally:
    comm.Barrier()
    if rank == 0:
        try:
            os.unlink(path)
        except OSError:
            pass
MPI.Finalize()
