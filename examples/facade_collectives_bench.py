"""mpi4py-facade vs native collective overhead microbench.

Uppercase buffer-API collectives through ``ompi_tpu.compat.MPI`` should
cost ~the native array API (the stacked-ndarray fast path skips the
per-rank python list round-trip mpi4py users would never expect from
uppercase calls).  Run standalone to see the ratio per collective:

    python examples/facade_collectives_bench.py

Exercised by tests/runtime/test_examples.py as a smoke; the ratio
assertion lives in tests/mpi/test_mpi4py_compat.py (1-core boxes make
wall-clock ratios here advisory, not CI-stable).
"""

import threading
import time

import numpy as np

from ompi_tpu.compat import MPI
from ompi_tpu.mpi.comm import Communicator
from ompi_tpu.mpi.group import Group
from ompi_tpu.mpi.pml import PmlOb1


def run_ranks(n, fn, timeout=300.0):
    """Minimal in-process n-rank rig (the tests/mpi/harness shape)."""
    pmls = [PmlOb1(r) for r in range(n)]
    addrs = {r: p.address for r, p in enumerate(pmls)}
    for p in pmls:
        p.set_peers(addrs)
    comms = [Communicator(Group(range(n)), cid=0, pml=pmls[r],
                          my_world_rank=r, name="bench")
             for r in range(n)]
    results = [None] * n
    errors = []

    def runner(r):
        try:
            results[r] = fn(comms[r])
        except BaseException as e:  # noqa: BLE001 — surface in main thread
            errors.append((r, e))

    ts = [threading.Thread(target=runner, args=(r,), daemon=True)
          for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    alive = [i for i, t in enumerate(ts) if t.is_alive()]
    if alive:
        raise TimeoutError(f"ranks {alive} did not finish in {timeout}s "
                           f"(errors so far: {errors})")
    for p in pmls:
        p.close()
    if errors:
        rank, exc = errors[0]
        raise AssertionError(f"rank {rank} failed: {exc!r}") from exc
    return results


N_RANKS = 4
ELEMS = 1 << 16          # 256 KiB float32 per rank
ITERS = 30


def bench(comm) -> dict:
    facade = MPI.Comm(comm)
    send = np.ones(ELEMS, np.float32) * (comm.rank + 1)
    recv_all = np.zeros(ELEMS * comm.size, np.float32)
    recv_one = np.zeros(ELEMS, np.float32)
    out: dict = {}

    def timed(fn) -> float:
        fn()                              # warm
        t0 = time.perf_counter()
        for _ in range(ITERS):
            fn()
        return (time.perf_counter() - t0) / ITERS

    out["native_allreduce"] = timed(lambda: comm.allreduce(send))
    out["facade_allreduce"] = timed(
        lambda: facade.Allreduce(send, recv_one))
    out["native_allgather"] = timed(lambda: comm.allgather(send))
    out["facade_allgather"] = timed(
        lambda: facade.Allgather(send, recv_all))
    out["native_bcast"] = timed(
        lambda: comm.bcast(send if comm.rank == 0 else None, 0))
    out["facade_bcast"] = timed(lambda: facade.Bcast(send, 0))
    return out


def main() -> None:
    rows = run_ranks(N_RANKS, bench, timeout=300.0)
    agg = {k: float(np.mean([r[k] for r in rows])) for k in rows[0]}
    print(f"{N_RANKS} ranks, {ELEMS * 4 >> 10} KiB/rank, "
          f"{ITERS} iters (mean per-call):")
    for coll in ("allreduce", "allgather", "bcast"):
        nat, fac = agg[f"native_{coll}"], agg[f"facade_{coll}"]
        print(f"  {coll:10s} native {nat * 1e6:8.1f}us   "
              f"facade {fac * 1e6:8.1f}us   ratio {fac / nat:5.2f}x")


if __name__ == "__main__":
    main()
